"""Cross-host actor transport — the piece that makes ``parallel.actors``
span a TPU pod the way the reference's RayOnSpark spanned a Spark cluster
(pyzoo/zoo/ray/util/raycontext.py:192-393: one raylet per executor host;
here, one :func:`start_worker_server` per pod host).

Wire design: the driver keeps ONE TCP connection per remote actor (the
ordering guarantee of the actor model falls out of TCP's in-order
delivery, exactly as the local path's pipe gives it).  The first message
on a fresh connection is the cloudpickled ``(cls, args, kwargs)`` spawn
payload; the worker server spawns the actor as a local **spawn** process
(same fork-safety contract as single-host actors) and then shuttles
messages between socket and pipe until either side closes.  Frames are
``struct`` length-prefixed pickles — the same (call_id, method, args,
kwargs) tuples the local path uses, so :class:`actors.ActorHandle` drives
both transports unchanged.

Launch on each host (the role of ``ray start`` in raycontext.py):

    python -m analytics_zoo_tpu.parallel.actor_worker --port 9040

then on the driver::

    ActorContext.init(workers=["host1:9040", "host2:9040"])
    h = MyActor.options(worker="host2:9040").remote(...)
    # or worker=1 (index into the registered list), or unset: round-robin

SECURITY (ADVICE r05 medium): frames are pickle, so a reachable port is
arbitrary code execution for whoever can speak the protocol.  Three
layers of defence:

- the server binds **127.0.0.1 by default**; a non-loopback bind (pod
  use) must be requested explicitly;
- a **mutual shared-secret handshake** runs before any unpickling ON
  EITHER END: the server's first frame is a raw (non-pickle) hello
  announcing its auth mode; with a secret it carries a random challenge,
  the client answers with a fresh nonce plus
  ``HMAC-SHA256(secret, client_ctx || challenge || nonce)``, and the
  server must respond with
  ``HMAC-SHA256(secret, server_ctx || challenge || nonce)`` before the
  driver sends (or unpickles) anything — a spoofed worker endpoint
  cannot produce the server proof itself (it could only relay a live
  handshake to a real worker, which is the on-path case below), and
  the per-side nonces make both proofs non-replayable.  A secret-presence mismatch between the two
  ends fails immediately with a clear error.  The secret comes from
  ``ZOO_ACTOR_SECRET`` on both ends (or the ``secret=`` argument); set
  it on every pod host.
- binding a non-loopback address WITHOUT a secret raises unless
  ``allow_unauthenticated=True`` is passed (the explicit "I know this
  port is open RCE on a trusted private interconnect" opt-in).

Threat model: the handshake stops UNAUTHENTICATED peers (port scanners,
spoofed endpoints, secretless clients) from reaching either side's
deserializer.  Post-handshake frames are NOT individually MACed or
encrypted, so an active on-path attacker — one who can splice into an
established connection, or relay a live handshake between the driver
and a real worker and then inject its own frames — is out of scope: the
transport trusts the network path, exactly like Ray's raylet protocol;
run pod traffic on a private interconnect or under WireGuard/TLS if the
path itself is hostile.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import socket
import struct
import threading

_LEN = struct.Struct(">Q")

_CLIENT_CONTEXT = b"zoo-actor-auth-client-v1"
_SERVER_CONTEXT = b"zoo-actor-auth-server-v1"
_LOOPBACK = ("127.0.0.1", "localhost", "::1")
# Server's first (raw, non-pickle) frame announces the auth mode, so a
# secret-presence mismatch between driver and worker fails instantly
# with a clear error instead of a 30s hang waiting for a frame the
# other side will never send.
_HELLO_AUTH = b"zoo-hello-1 auth "  # + 32-byte challenge
_HELLO_OPEN = b"zoo-hello-1 open"


def _client_proof(secret: bytes, challenge: bytes,
                  nonce: bytes) -> bytes:
    """Driver's answer to the server's challenge; the fresh client nonce
    keeps it non-replayable even against a reused challenge."""
    return hmac.new(secret, _CLIENT_CONTEXT + challenge + nonce,
                    hashlib.sha256).digest()


def _server_proof(secret: bytes, challenge: bytes,
                  nonce: bytes) -> bytes:
    """Server's proof it knows the secret too (distinct context string,
    bound to the client's nonce): the driver verifies this BEFORE
    unpickling any reply, so a spoofed worker endpoint never reaches the
    driver-side deserializer."""
    return hmac.new(secret, _SERVER_CONTEXT + challenge + nonce,
                    hashlib.sha256).digest()


def _resolve_secret(secret) -> bytes | None:
    """Explicit arg > ZOO_ACTOR_SECRET env > None (no handshake)."""
    if secret is None:
        env = os.environ.get("ZOO_ACTOR_SECRET")
        return env.encode() if env else None
    return secret.encode() if isinstance(secret, str) else bytes(secret)


class SockConn:
    """Pipe-shaped adapter over a socket: send/recv/poll/close — the
    surface ``ActorHandle`` needs, so it can drive either transport.

    ``poll`` reports True only when a FULL frame is buffered (on a pipe,
    poll-true implies a whole message; a raw socket select() only means
    *some* bytes arrived — treating that as message-ready would let a
    stalled peer that sent half a frame hang ``get(timeout)`` forever).
    The receive buffer is a bytearray (amortized O(n) accumulation, not
    O(n²) bytes concatenation — parameter-server replies are large)."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = bytearray()

    def send(self, obj):
        self.send_bytes(pickle.dumps(obj))

    def send_bytes(self, payload: bytes):
        """One raw length-prefixed frame (no pickle — the pre-auth
        handshake must not involve the deserializer at all)."""
        self._sock.sendall(_LEN.pack(len(payload)) + payload)

    def _frame_len(self):
        """Length of the buffered frame, or None if incomplete."""
        if len(self._buf) < _LEN.size:
            return None
        (n,) = _LEN.unpack(bytes(self._buf[:_LEN.size]))
        return n if len(self._buf) >= _LEN.size + n else None

    def _fill(self, timeout, max_len: int | None = None) -> bool:
        """Buffer until a full frame is present; False on timeout.
        ``max_len`` rejects oversized frames from the HEADER, before the
        body is buffered (pre-auth flood guard)."""
        import select
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while self._frame_len() is None:
            if max_len is not None and len(self._buf) >= _LEN.size:
                (n,) = _LEN.unpack(bytes(self._buf[:_LEN.size]))
                if n > max_len:
                    raise ValueError(f"frame of {n} bytes exceeds "
                                     f"pre-auth limit {max_len}")
            remaining = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            r, _, _ = select.select([self._sock], [], [], remaining)
            if not r:
                return False
            chunk = self._sock.recv(1 << 20)
            if not chunk:
                raise EOFError("actor connection closed")
            self._buf += chunk
        return True

    def recv(self):
        return pickle.loads(self.recv_bytes())

    def recv_bytes(self, timeout=None, max_len: int | None = None):
        """One raw frame.  ``max_len`` bounds pre-auth frames so an
        unauthenticated peer cannot make the server buffer gigabytes."""
        if not self._fill(timeout, max_len=max_len):
            raise TimeoutError("actor frame timed out")
        n = self._frame_len()
        if max_len is not None and n > max_len:
            # frame arrived whole in one recv: the header short-circuit
            # in _fill never ran
            raise ValueError(f"frame of {n} bytes exceeds pre-auth "
                             f"limit {max_len}")
        payload = bytes(self._buf[_LEN.size:_LEN.size + n])
        del self._buf[:_LEN.size + n]
        return payload

    def poll(self, timeout=None) -> bool:
        return self._fill(timeout)

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def _serve_connection(sock: socket.socket, secret: bytes | None = None):
    """One accepted driver connection == one actor lifetime."""
    import multiprocessing as mp

    conn = SockConn(sock)
    proc = None
    try:
        if secret is not None:
            # Mutual challenge-response BEFORE any unpickling: raw
            # frames only.  Client reply = 32-byte nonce || proof; the
            # server's counter-proof goes back only to an authenticated
            # client (leaking it to anyone would be a proof oracle).
            challenge = os.urandom(32)
            conn.send_bytes(_HELLO_AUTH + challenge)
            try:
                reply = conn.recv_bytes(timeout=10, max_len=64)
            except (TimeoutError, ValueError, EOFError, OSError):
                conn.close()
                return
            nonce, proof = reply[:32], reply[32:]
            if not hmac.compare_digest(
                    proof, _client_proof(secret, challenge, nonce)):
                conn.close()
                return
            conn.send_bytes(_server_proof(secret, challenge, nonce))
        else:
            conn.send_bytes(_HELLO_OPEN)
        kind, payload = conn.recv()
        if kind == "__zoo_telemetry__":
            # Reserved control frame (ISSUE 2): the driver pulls THIS
            # worker-server process's telemetry (registry + health,
            # metrics/merge.py format) — one authed connection per pull,
            # answered post-handshake so unauthenticated peers never see
            # the snapshot either.
            from analytics_zoo_tpu.metrics.merge import telemetry_snapshot

            conn.send(("telemetry", telemetry_snapshot()))
            return
        if kind != "spawn":
            conn.send(("init_error", f"bad first frame {kind!r}"))
            return
        try:
            from analytics_zoo_tpu.parallel.actors import _actor_loop

            spawn = mp.get_context("spawn")
            parent, child = spawn.Pipe()
            proc = spawn.Process(target=_actor_loop,
                                 args=(payload, child), daemon=True)
            proc.start()
            child.close()
        except Exception:
            # surface spawn failures as the same init_error frame the
            # local path produces (an ActorError with traceback on the
            # driver), and keep the server-side record
            import traceback

            tb = traceback.format_exc()
            print(f"actor spawn failed:\n{tb}", file=__import__("sys")
                  .stderr)
            conn.send(("init_error", tb))
            return

        # pipe -> socket pump in a side thread; socket -> pipe inline
        def pump():
            try:
                while True:
                    conn.send(parent.recv())
            except (EOFError, OSError):
                conn.close()

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        try:
            while True:
                msg = conn.recv()
                parent.send(msg)
                if msg is None:  # shutdown sentinel, same as local path
                    break
        except EOFError:
            pass
        proc.join(timeout=5)
    except EOFError:
        pass  # driver went away: normal teardown
    except Exception:
        import sys
        import traceback

        print(f"actor connection error:\n{traceback.format_exc()}",
              file=sys.stderr)
    finally:
        if proc is not None and proc.is_alive():
            proc.terminate()
        conn.close()


def start_worker_server(port: int, bind: str = "127.0.0.1",
                        block: bool = True, secret=None,
                        allow_unauthenticated: bool = False):
    """Accept actor placements on this host (the raylet role).  With
    ``block=False`` returns the listening socket and serves from a
    daemon thread (tests / embedding in a launcher).

    Binds loopback by default.  A non-loopback ``bind`` (pod use)
    requires a shared ``secret`` (arg or ``ZOO_ACTOR_SECRET`` env) so
    unauthenticated peers never reach the pickle layer — or the explicit
    ``allow_unauthenticated=True`` opt-in for a physically private
    interconnect."""
    secret = _resolve_secret(secret)
    if bind not in _LOOPBACK and secret is None \
            and not allow_unauthenticated:
        raise ValueError(
            f"binding {bind!r} exposes a pickle endpoint (code "
            "execution) to the network: set a shared secret "
            "(ZOO_ACTOR_SECRET or secret=) or pass "
            "allow_unauthenticated=True to opt in explicitly")
    srv = socket.create_server((bind, port), reuse_port=False)

    def loop():
        while True:
            try:
                sock, _ = srv.accept()
            except OSError:  # closed
                return
            threading.Thread(target=_serve_connection,
                             args=(sock, secret), daemon=True).start()

    if block:
        loop()  # returns only when the listen socket dies/closes
        return srv
    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return srv


def _connect_authed(addr: str, secret, timeout: float = 30) -> SockConn:
    """Open one authenticated connection to a worker server (the mutual
    HMAC handshake from the module doc, shared verbatim by actor spawns
    and telemetry pulls).  The server's hello frame announces its auth
    mode; a secret-presence mismatch (arg or ``ZOO_ACTOR_SECRET`` on one
    end only) raises immediately with the fix spelled out instead of
    hanging until timeout."""
    secret = _resolve_secret(secret)
    host, port = addr.rsplit(":", 1)
    conn = SockConn(socket.create_connection((host, int(port)),
                                             timeout=timeout))
    conn._sock.settimeout(None)
    try:
        hello = conn.recv_bytes(timeout=timeout, max_len=64)
        if hello.startswith(_HELLO_AUTH):
            if secret is None:
                raise RuntimeError(
                    f"worker {addr} requires a shared secret; set "
                    "ZOO_ACTOR_SECRET (to the worker's value) or pass "
                    "secret= to connect")
            challenge = hello[len(_HELLO_AUTH):]
            nonce = os.urandom(32)
            conn.send_bytes(nonce + _client_proof(secret, challenge,
                                                  nonce))
            # the server must prove it knows the secret too, BEFORE we
            # unpickle anything it sends: a spoofed endpoint on a dead
            # worker's port cannot forge this.  A server that closed
            # instead of answering rejected OUR proof — surface that as
            # the auth failure it is, not a bare connection error
            try:
                counter = conn.recv_bytes(timeout=timeout, max_len=64)
            except (EOFError, TimeoutError, OSError) as e:
                raise RuntimeError(
                    f"worker {addr} dropped the connection during the "
                    "auth handshake — usually a WRONG shared secret "
                    "(ZOO_ACTOR_SECRET values differ between driver "
                    "and worker)") from e
            if not hmac.compare_digest(
                    counter, _server_proof(secret, challenge, nonce)):
                raise RuntimeError(
                    f"worker {addr} failed to prove knowledge of the "
                    "shared secret (wrong ZOO_ACTOR_SECRET on the "
                    "worker, or a spoofed endpoint): refusing to "
                    "deserialize its replies")
        elif hello == _HELLO_OPEN:
            if secret is not None:
                raise RuntimeError(
                    f"worker {addr} runs unauthenticated but this "
                    "driver has a secret configured (ZOO_ACTOR_SECRET "
                    "or secret=): refusing the downgrade — restart the "
                    "worker with the same secret, or connect with "
                    "secret=None after unsetting ZOO_ACTOR_SECRET")
        else:
            raise RuntimeError(
                f"worker {addr} sent unrecognized hello {hello[:24]!r} "
                "— not a zoo actor worker (or a version mismatch)")
    except BaseException:
        conn.close()
        raise
    return conn


def connect_and_spawn(addr: str, payload: bytes,
                      secret=None) -> SockConn:
    """Driver side: open the actor's connection and send the spawn
    payload; returns the live conn (first reply is the ready/err frame,
    read by ActorHandle exactly as on the local path)."""
    conn = _connect_authed(addr, secret)
    conn.send(("spawn", payload))
    return conn


def fetch_worker_telemetry(addr: str, secret=None,
                           timeout: float = 30) -> dict:
    """Pull the worker SERVER process's telemetry snapshot (registry +
    health, metrics/merge.py format) over one authed connection carrying
    the reserved ``__zoo_telemetry__`` frame.  Complements per-actor
    pulls (``ActorHandle.telemetry``): spawned actors answer for
    themselves; this answers for the server that hosts them."""
    conn = _connect_authed(addr, secret, timeout=timeout)
    try:
        conn.send(("__zoo_telemetry__", None))
        if not conn.poll(timeout):
            raise TimeoutError(f"worker {addr} telemetry timed out")
        kind, snap = conn.recv()
        if kind != "telemetry":
            raise RuntimeError(
                f"worker {addr} answered {kind!r} to a telemetry pull "
                "(version mismatch?)")
        return snap
    finally:
        conn.close()


def main():
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int, default=9040)
    p.add_argument("--bind", default="127.0.0.1",
                   help="listen address; non-loopback requires "
                        "ZOO_ACTOR_SECRET or --allow-unauthenticated")
    p.add_argument("--allow-unauthenticated", action="store_true",
                   help="serve a non-loopback bind WITHOUT a shared "
                        "secret (trusted private interconnect only)")
    a = p.parse_args()
    print(f"actor worker serving on {a.bind}:{a.port}")
    start_worker_server(a.port, a.bind,
                        allow_unauthenticated=a.allow_unauthenticated)


if __name__ == "__main__":
    main()
