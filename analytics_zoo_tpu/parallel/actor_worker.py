"""Cross-host actor transport — the piece that makes ``parallel.actors``
span a TPU pod the way the reference's RayOnSpark spanned a Spark cluster
(pyzoo/zoo/ray/util/raycontext.py:192-393: one raylet per executor host;
here, one :func:`start_worker_server` per pod host).

Wire design: the driver keeps ONE TCP connection per remote actor (the
ordering guarantee of the actor model falls out of TCP's in-order
delivery, exactly as the local path's pipe gives it).  The first message
on a fresh connection is the cloudpickled ``(cls, args, kwargs)`` spawn
payload; the worker server spawns the actor as a local **spawn** process
(same fork-safety contract as single-host actors) and then shuttles
messages between socket and pipe until either side closes.  Frames are
``struct`` length-prefixed pickles — the same (call_id, method, args,
kwargs) tuples the local path uses, so :class:`actors.ActorHandle` drives
both transports unchanged.

Launch on each host (the role of ``ray start`` in raycontext.py):

    python -m analytics_zoo_tpu.parallel.actor_worker --port 9040

then on the driver::

    ActorContext.init(workers=["host1:9040", "host2:9040"])
    h = MyActor.options(worker="host2:9040").remote(...)
    # or worker=1 (index into the registered list), or unset: round-robin

SECURITY: frames are pickle — run worker servers only on a trusted,
private interconnect (the TPU pod network), exactly like Ray's raylet
protocol.  The server binds 0.0.0.0 by default for pod use; bind
127.0.0.1 for local testing.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading

_LEN = struct.Struct(">Q")


class SockConn:
    """Pipe-shaped adapter over a socket: send/recv/poll/close — the
    surface ``ActorHandle`` needs, so it can drive either transport.

    ``poll`` reports True only when a FULL frame is buffered (on a pipe,
    poll-true implies a whole message; a raw socket select() only means
    *some* bytes arrived — treating that as message-ready would let a
    stalled peer that sent half a frame hang ``get(timeout)`` forever).
    The receive buffer is a bytearray (amortized O(n) accumulation, not
    O(n²) bytes concatenation — parameter-server replies are large)."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = bytearray()

    def send(self, obj):
        payload = pickle.dumps(obj)
        self._sock.sendall(_LEN.pack(len(payload)) + payload)

    def _frame_len(self):
        """Length of the buffered frame, or None if incomplete."""
        if len(self._buf) < _LEN.size:
            return None
        (n,) = _LEN.unpack(bytes(self._buf[:_LEN.size]))
        return n if len(self._buf) >= _LEN.size + n else None

    def _fill(self, timeout) -> bool:
        """Buffer until a full frame is present; False on timeout."""
        import select
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while self._frame_len() is None:
            remaining = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            r, _, _ = select.select([self._sock], [], [], remaining)
            if not r:
                return False
            chunk = self._sock.recv(1 << 20)
            if not chunk:
                raise EOFError("actor connection closed")
            self._buf += chunk
        return True

    def recv(self):
        self._fill(None)
        n = self._frame_len()
        payload = bytes(self._buf[_LEN.size:_LEN.size + n])
        del self._buf[:_LEN.size + n]
        return pickle.loads(payload)

    def poll(self, timeout=None) -> bool:
        return self._fill(timeout)

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def _serve_connection(sock: socket.socket):
    """One accepted driver connection == one actor lifetime."""
    import multiprocessing as mp

    conn = SockConn(sock)
    proc = None
    try:
        kind, payload = conn.recv()
        if kind != "spawn":
            conn.send(("init_error", f"bad first frame {kind!r}"))
            return
        try:
            from analytics_zoo_tpu.parallel.actors import _actor_loop

            spawn = mp.get_context("spawn")
            parent, child = spawn.Pipe()
            proc = spawn.Process(target=_actor_loop,
                                 args=(payload, child), daemon=True)
            proc.start()
            child.close()
        except Exception:
            # surface spawn failures as the same init_error frame the
            # local path produces (an ActorError with traceback on the
            # driver), and keep the server-side record
            import traceback

            tb = traceback.format_exc()
            print(f"actor spawn failed:\n{tb}", file=__import__("sys")
                  .stderr)
            conn.send(("init_error", tb))
            return

        # pipe -> socket pump in a side thread; socket -> pipe inline
        def pump():
            try:
                while True:
                    conn.send(parent.recv())
            except (EOFError, OSError):
                conn.close()

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        try:
            while True:
                msg = conn.recv()
                parent.send(msg)
                if msg is None:  # shutdown sentinel, same as local path
                    break
        except EOFError:
            pass
        proc.join(timeout=5)
    except EOFError:
        pass  # driver went away: normal teardown
    except Exception:
        import sys
        import traceback

        print(f"actor connection error:\n{traceback.format_exc()}",
              file=sys.stderr)
    finally:
        if proc is not None and proc.is_alive():
            proc.terminate()
        conn.close()


def start_worker_server(port: int, bind: str = "0.0.0.0",
                        block: bool = True):
    """Accept actor placements on this host (the raylet role).  With
    ``block=False`` returns the listening socket and serves from a
    daemon thread (tests / embedding in a launcher)."""
    srv = socket.create_server((bind, port), reuse_port=False)

    def loop():
        while True:
            try:
                sock, _ = srv.accept()
            except OSError:  # closed
                return
            threading.Thread(target=_serve_connection, args=(sock,),
                             daemon=True).start()

    if block:
        loop()  # returns only when the listen socket dies/closes
        return srv
    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return srv


def connect_and_spawn(addr: str, payload: bytes) -> SockConn:
    """Driver side: open the actor's connection and send the spawn
    payload; returns the live conn (first reply is the ready/err frame,
    read by ActorHandle exactly as on the local path)."""
    host, port = addr.rsplit(":", 1)
    conn = SockConn(socket.create_connection((host, int(port)),
                                             timeout=30))
    conn._sock.settimeout(None)
    conn.send(("spawn", payload))
    return conn


def main():
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int, default=9040)
    p.add_argument("--bind", default="0.0.0.0")
    a = p.parse_args()
    print(f"actor worker serving on {a.bind}:{a.port}")
    start_worker_server(a.port, a.bind)


if __name__ == "__main__":
    main()
