"""GPipe-style pipeline parallelism over the ``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.4: PP "No"); this
module completes the framework's DP x TP x SP x EP x PP mesh-axis matrix.

Design (TPU-idiomatic, not a scheduler translation): one pipeline stage per
device along the ``pipe`` axis; the microbatch schedule is a single
``lax.scan`` over ticks inside ``shard_map``, with ``lax.ppermute`` shifting
activations one ICI hop to the next stage each tick.  Because the whole
schedule is scan + ppermute, ``jax.grad`` of the pipelined forward IS the
reverse pipeline — no hand-written backward schedule, and the bubble
(S - 1 idle ticks at fill/drain) is the standard GPipe bubble.

Contrast with the reference's execution model: BigDL runs the whole model on
every Spark task and all-reduces gradients (wp-bigdl.md:148-164).  Here the
model's *layers* are sharded across chips, so models larger than one chip's
HBM train without resharding the optimizer.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.common.engine import PIPE_AXIS, get_zoo_context


def _pipeline_local(stage_params, x_mb, *, stage_fn, axis_name, n_stages,
                    n_micro):
    """Per-shard GPipe schedule.

    stage_params: this shard's stage weights, leading dim 1 (stage-sharded).
    x_mb: (M, mb, ...) microbatches, replicated over the pipe axis; stage 0
      injects x_mb[t] at tick t.
    Returns (M, mb, ...) final-stage outputs, replicated over the pipe axis.
    """
    idx = lax.axis_index(axis_name)
    p_local = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    n_ticks = n_micro + n_stages - 1

    def tick(carry, t):
        # carry: the activation that arrived at this stage from the previous
        # stage last tick.  Stage 0 ignores it and injects the next
        # microbatch instead (clamped past the end: those outputs can never
        # reach the last stage inside the valid tick window, so they are
        # dead compute with zero cotangent, not a correctness hazard).
        inj = x_mb[jnp.clip(t, 0, n_micro - 1)]
        act = jnp.where(idx == 0, inj, carry)
        out = stage_fn(p_local, act)
        shifted = lax.ppermute(out, axis_name, perm)
        return shifted, out

    _, ys = lax.scan(tick, jnp.zeros_like(x_mb[0]), jnp.arange(n_ticks))
    # Stage s processes microbatch t - s at tick t, so the last stage emits
    # microbatch m at tick m + n_stages - 1: the ordered outputs are the
    # last stage's ys[n_stages-1:].  Mask+psum replicates them everywhere so
    # the loss (and jax.grad) is an ordinary SPMD computation.
    valid = ys[n_stages - 1:]
    return lax.psum(
        jnp.where(idx == n_stages - 1, valid, jnp.zeros_like(valid)),
        axis_name,
    )


def gpipe(stage_fn, stage_params, x, *, n_microbatch, mesh=None,
          axis_name: str = PIPE_AXIS, batch_axis: str | None = None):
    """Microbatched pipeline-parallel application of a stage stack.

    Args:
      stage_fn: ``(params_one_stage, act) -> act`` — one pipeline stage;
        activations must keep one shape across stages (pad/project inside
        the stage if needed), the usual contract for scanned stacks.
      stage_params: pytree whose leaves have leading dim ``n_stages`` (==
        the ``pipe`` axis size), stage i's weights at index i.  Under jit,
        shard the leading dim over ``pipe``.
      x: (B, ...) global batch; B must divide by ``n_microbatch`` (and by
        ``n_microbatch * batch_axis size`` when composing with DP).
      n_microbatch: GPipe microbatch count M; bubble fraction is
        (S-1)/(M+S-1), so pick M >= ~4*S.
      batch_axis: mesh axis to data-parallelize over (e.g. ``"data"``).
        Each microbatch's rows are sharded over it, so every data shard
        pipelines only its own rows — PP x DP composition.  Differentiating
        through the replicated ``stage_params`` in_spec automatically psums
        the per-shard parameter cotangents over ``batch_axis`` (shard_map's
        transpose of replication), i.e. the DP gradient all-reduce needs no
        explicit collective here.  None = batch replicated over every
        non-pipe axis.
    Returns:
      (B, ...) outputs of the last stage, replicated over the pipe axis
      (row-sharded over ``batch_axis`` when given).
    """
    mesh = mesh or get_zoo_context().mesh
    n_stages = dict(mesh.shape).get(axis_name, 1)
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != pipe axis "
                f"size {n_stages} (leaf shape {leaf.shape})"
            )
    b = x.shape[0]
    if b % n_microbatch:
        raise ValueError(f"batch {b} not divisible by M={n_microbatch}")
    if n_stages == 1:
        one = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        return stage_fn(one, x)
    x_mb = x.reshape((n_microbatch, b // n_microbatch) + x.shape[1:])
    mb_spec = P(None, batch_axis)  # rows of each microbatch over DP axis
    fn = jax.shard_map(
        partial(_pipeline_local, stage_fn=stage_fn, axis_name=axis_name,
                n_stages=n_stages, n_micro=n_microbatch),
        mesh=mesh,
        in_specs=(P(axis_name), mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )
    out = fn(stage_params, x_mb)
    return out.reshape((b,) + out.shape[2:])


def stack_stage_params(per_stage: list):
    """Stack a list of identically-structured per-stage param pytrees into
    the leading-stage-dim layout ``gpipe`` expects."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage
    )


def transformer_gpipe(layer, params, h, *, n_microbatch, mask=None,
                      mesh=None, axis_name: str = PIPE_AXIS,
                      batch_axis=None):
    """Run a transformer block stack (TransformerLayer/BERT core) as a
    GPipe pipeline: block i's weights live on pipe shard i.

    ``layer.n_block`` must equal the pipe axis size; ``h`` is the
    post-embedding activation (B, L, D) — embeddings and the head stay
    replicated (they are the small ends of the model; the block stack is
    what outgrows one chip's HBM).  ``mask`` is an additive attention mask
    closed over every stage; because the schedule re-slices the batch into
    microbatches, only batch-independent masks are expressible (shape
    (L, L) or (1, 1, L, L) — shared structural masks).  Per-sample padding
    masks (leading batch dim > 1, the BERT padded-batch case) are
    rejected: they cannot follow the microbatch slicing through a closure.
    Blocks run in inference mode (dropout off); the scan+ppermute schedule
    is shared with :func:`gpipe`, so jax.grad still yields the reverse
    pipeline for training use, and ``layer.remat=True`` is honored per
    stage.
    """
    if mask is not None and mask.ndim >= 3 and mask.shape[0] != 1:
        raise ValueError(
            "transformer_gpipe: per-sample masks (leading batch dim "
            f"{mask.shape[0]}) cannot follow the microbatch schedule; "
            "only batch-independent masks are supported")
    blocks = params["blocks"] if isinstance(params, dict) else params
    stacked = stack_stage_params(list(blocks))

    def stage_fn(bp, act):
        return layer._block_forward(bp, act, mask, False, None)

    if layer.remat:
        stage_fn = jax.checkpoint(stage_fn)

    return gpipe(stage_fn, stacked, h, n_microbatch=n_microbatch,
                 mesh=mesh, axis_name=axis_name, batch_axis=batch_axis)
