"""GPipe-style pipeline parallelism over the ``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.4: PP "No"); this
module completes the framework's DP x TP x SP x EP x PP mesh-axis matrix.

Design (TPU-idiomatic, not a scheduler translation): one pipeline stage per
device along the ``pipe`` axis; the microbatch schedule is a single
``lax.scan`` over ticks inside ``shard_map``, with ``lax.ppermute`` shifting
activations one ICI hop to the next stage each tick.  Because the whole
schedule is scan + ppermute, ``jax.grad`` of the pipelined forward IS the
reverse pipeline — no hand-written backward schedule, and the bubble
(S - 1 idle ticks at fill/drain) is the standard GPipe bubble.

Contrast with the reference's execution model: BigDL runs the whole model on
every Spark task and all-reduces gradients (wp-bigdl.md:148-164).  Here the
model's *layers* are sharded across chips, so models larger than one chip's
HBM train without resharding the optimizer.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.common.engine import PIPE_AXIS, get_zoo_context
from analytics_zoo_tpu.metrics import get_registry


def _record_schedule(schedule: str, n_stages: int, n_micro: int,
                     bubble_ticks: int, total_ticks: int):
    """Publish the schedule's bubble structure to the metrics registry.

    The schedule runs INSIDE jit, so host wall-clock per microbatch is
    unobservable here; what is exact (and what a capacity planner needs)
    is the analytic bubble: idle fill/drain ticks per schedule, per
    microbatch, and as a fraction of total ticks.  Recorded once per
    trace (the call site executes at trace time), labeled by schedule."""
    reg = get_registry()
    labels = ("schedule",)
    reg.gauge("zoo_pipeline_stages", "pipeline stage count",
              labels).labels(schedule=schedule).set(n_stages)
    reg.gauge("zoo_pipeline_microbatches", "microbatch count M",
              labels).labels(schedule=schedule).set(n_micro)
    reg.gauge("zoo_pipeline_bubble_fraction",
              "idle fill/drain ticks / total schedule ticks",
              labels).labels(schedule=schedule).set(
                  bubble_ticks / max(total_ticks, 1))
    reg.gauge("zoo_pipeline_bubble_ticks_per_microbatch",
              "per-microbatch bubble time in stage-tick units",
              labels).labels(schedule=schedule).set(
                  bubble_ticks / max(n_micro, 1))


# compiled-schedule cache for eager entry: key -> PlannedStep, so a
# training loop calling a schedule repeatedly re-dispatches the cached
# executable instead of re-lowering every step (the choke point's
# signature probe handles shape churn per entry)
_PLANNED_CACHE: dict = {}
_PLANNED_CACHE_MAX = 32


def _args_sig(args) -> tuple:
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (treedef, tuple(
        (tuple(getattr(l, "shape", ())), str(getattr(l, "dtype", type(l))))
        for l in leaves))


def _run_planned(local, schedule, mesh, in_specs, out_specs, fns_key,
                 args):
    """Run a per-shard schedule body through the compile choke point.

    Called eagerly, the schedule lowers via ``compile_step`` under a
    ``pipeline_<schedule>`` plan: per-plan compile label, persistent
    compile cache, ``zoo_hlo_*`` feature extraction — everything the
    other plans already get.  Called under someone ELSE's trace (the
    schedule composes inside jax.jit / jax.grad — test_pipeline_parallel
    pins it), the shard_map stages inline instead: the OUTER program
    owns the choke point, and nesting a second jit would break the
    grad-of-pipeline story."""
    if any(isinstance(l, jax.core.Tracer)
           for l in jax.tree_util.tree_leaves(args)):
        fn = jax.shard_map(local, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
        return fn(*args)
    from analytics_zoo_tpu.parallel.plan import compile_step, pipeline_plan

    key = (schedule, mesh, fns_key, in_specs, out_specs, _args_sig(args))
    step = _PLANNED_CACHE.get(key)
    if step is None:
        step = compile_step(local, pipeline_plan(schedule), mesh,
                            in_specs=in_specs, out_specs=out_specs,
                            check_vma=False,
                            label=f"pipeline_{schedule}_step",
                            meta={"mesh_shape": dict(mesh.shape),
                                  "schedule": schedule})
        while len(_PLANNED_CACHE) >= _PLANNED_CACHE_MAX:
            _PLANNED_CACHE.pop(next(iter(_PLANNED_CACHE)))
        _PLANNED_CACHE[key] = step
    return step(*args)


def _pipeline_local(stage_params, x_mb, *, stage_fn, axis_name, n_stages,
                    n_micro):
    """Per-shard GPipe schedule.

    stage_params: this shard's stage weights, leading dim 1 (stage-sharded).
    x_mb: (M, mb, ...) microbatches, replicated over the pipe axis; stage 0
      injects x_mb[t] at tick t.
    Returns (M, mb, ...) final-stage outputs, replicated over the pipe axis.
    """
    idx = lax.axis_index(axis_name)
    p_local = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    n_ticks = n_micro + n_stages - 1

    def tick(carry, t):
        # carry: the activation that arrived at this stage from the previous
        # stage last tick.  Stage 0 ignores it and injects the next
        # microbatch instead (clamped past the end: those outputs can never
        # reach the last stage inside the valid tick window, so they are
        # dead compute with zero cotangent, not a correctness hazard).
        inj = x_mb[jnp.clip(t, 0, n_micro - 1)]
        act = jnp.where(idx == 0, inj, carry)
        out = stage_fn(p_local, act)
        shifted = lax.ppermute(out, axis_name, perm)
        return shifted, out

    _, ys = lax.scan(tick, jnp.zeros_like(x_mb[0]), jnp.arange(n_ticks))
    # Stage s processes microbatch t - s at tick t, so the last stage emits
    # microbatch m at tick m + n_stages - 1: the ordered outputs are the
    # last stage's ys[n_stages-1:].  Mask+psum replicates them everywhere so
    # the loss (and jax.grad) is an ordinary SPMD computation.
    valid = ys[n_stages - 1:]
    return lax.psum(
        jnp.where(idx == n_stages - 1, valid, jnp.zeros_like(valid)),
        axis_name,
    )


def gpipe(stage_fn, stage_params, x, *, n_microbatch, mesh=None,
          axis_name: str = PIPE_AXIS, batch_axis: str | None = None,
          circular_repeats: int = 1):
    """Microbatched pipeline-parallel application of a stage stack.

    Args:
      stage_fn: ``(params_one_stage, act) -> act`` — one pipeline stage;
        activations must keep one shape across stages (pad/project inside
        the stage if needed — or use :func:`gpipe_hetero` for free-form
        boundaries), the usual contract for scanned stacks.
      stage_params: pytree whose leaves have leading dim ``n_stages *
        circular_repeats``; virtual stage j's weights at index j.  Under
        jit, shard over ``pipe`` (with circular_repeats v, shard i holds
        the interleaved slices i, i+S, ..., i+(v-1)S).
      x: (B, ...) global batch; B must divide by ``n_microbatch`` (and by
        ``n_microbatch * batch_axis size`` when composing with DP).
      n_microbatch: GPipe microbatch count M; bubble fraction is
        (S-1)/(M+S-1), so pick M >= ~4*S.
      circular_repeats: v > 1 = interleaved/circular schedule: each shard
        hosts v non-adjacent virtual stages and the ring is traversed v
        times, shrinking the bubble to (S-1)/(vM+S-1) (Megatron
        interleaved-schedule bubble).  Requires M >= S.
      batch_axis: mesh axis to data-parallelize over (e.g. ``"data"``).
        Each microbatch's rows are sharded over it, so every data shard
        pipelines only its own rows — PP x DP composition.  Differentiating
        through the replicated ``stage_params`` in_spec automatically psums
        the per-shard parameter cotangents over ``batch_axis`` (shard_map's
        transpose of replication), i.e. the DP gradient all-reduce needs no
        explicit collective here.  None = batch replicated over every
        non-pipe axis.
    Returns:
      (B, ...) outputs of the last stage, replicated over the pipe axis
      (row-sharded over ``batch_axis`` when given).
    """
    mesh = mesh or get_zoo_context().mesh
    n_stages = dict(mesh.shape).get(axis_name, 1)
    v = int(circular_repeats)
    n_virtual = n_stages * v
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_virtual:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != pipe axis "
                f"size {n_stages} * circular_repeats {v} "
                f"(leaf shape {leaf.shape})"
            )
    b = x.shape[0]
    if b % n_microbatch:
        raise ValueError(f"batch {b} not divisible by M={n_microbatch}")
    if n_stages == 1:
        out = x
        for j in range(n_virtual):
            one = jax.tree_util.tree_map(lambda a, _j=j: a[_j],
                                         stage_params)
            out = stage_fn(one, out)
        return out
    if v > 1 and n_microbatch < n_stages:
        raise ValueError(
            f"circular schedule needs n_microbatch >= pipe size "
            f"({n_microbatch} < {n_stages})")
    x_mb = x.reshape((n_microbatch, b // n_microbatch) + x.shape[1:])
    _record_schedule("gpipe" if v == 1 else "gpipe_circular",
                     n_stages, n_microbatch, n_stages - 1,
                     v * n_microbatch + n_stages - 1)
    mb_spec = P(None, batch_axis)  # rows of each microbatch over DP axis
    if v == 1:
        local = partial(_pipeline_local, stage_fn=stage_fn,
                        axis_name=axis_name, n_stages=n_stages,
                        n_micro=n_microbatch)
        p_arg = stage_params
        p_spec = P(axis_name)
    else:
        local = partial(_pipeline_local_circular, stage_fn=stage_fn,
                        axis_name=axis_name, n_stages=n_stages,
                        n_micro=n_microbatch, repeats=v)
        # (v*S, ...) -> (v, S, ...): round-major so shard i's rows are the
        # interleaved virtual stages i, i+S, ...
        p_arg = jax.tree_util.tree_map(
            lambda a: a.reshape((v, n_stages) + a.shape[1:]), stage_params)
        p_spec = P(None, axis_name)
    out = _run_planned(local, "gpipe" if v == 1 else "gpipe_circular",
                       mesh, (p_spec, mb_spec), mb_spec,
                       (stage_fn, v), (p_arg, x_mb))
    return out.reshape((b,) + out.shape[2:])


# ---------------------------------------------------------------------------
# Heterogeneous (non-shape-preserving) pipelines: union-buffer carry
# ---------------------------------------------------------------------------


def _is_int(dtype) -> bool:
    return jnp.issubdtype(dtype, jnp.bool_) or \
        jnp.issubdtype(dtype, jnp.integer)


def _pair_sizes(struct) -> tuple[int, int]:
    """(float_size, int_size) of a boundary struct: float and integer/bool
    leaves travel in SEPARATE buffers — floats in a differentiable f32
    vector, ints in an exact int32 vector (a float psum of bitcast int
    payloads would corrupt bit patterns that alias f32 NaN/-0.0, and a
    bitcast round-trip would sever gradient flow)."""
    import math

    f = i = 0
    for s in jax.tree_util.tree_leaves(struct):
        if _is_int(s.dtype):
            i += math.prod(s.shape)
        else:
            f += math.prod(s.shape)
    return f, i


def _encode(tree, flen: int, ilen: int):
    """Flatten a pytree into (f32 vector, int32 vector), zero-padded."""
    fparts, iparts = [], []
    for a in jax.tree_util.tree_leaves(tree):
        if _is_int(a.dtype):
            iparts.append(a.astype(jnp.int32).reshape(-1))
        else:
            fparts.append(a.astype(jnp.float32).reshape(-1))
    fv = (jnp.concatenate(fparts) if fparts
          else jnp.zeros((0,), jnp.float32))
    iv = (jnp.concatenate(iparts) if iparts
          else jnp.zeros((0,), jnp.int32))
    return (jnp.pad(fv, (0, flen - fv.shape[0])),
            jnp.pad(iv, (0, ilen - iv.shape[0])))


def _decode(bufs, struct):
    """Inverse of :func:`_encode` for the given ShapeDtypeStruct pytree."""
    import math

    fbuf, ibuf = bufs
    leaves, treedef = jax.tree_util.tree_flatten(struct)
    out, foff, ioff = [], 0, 0
    for s in leaves:
        n = math.prod(s.shape)
        if _is_int(s.dtype):
            seg = ibuf[ioff:ioff + n].reshape(s.shape).astype(s.dtype)
            ioff += n
        else:
            seg = fbuf[foff:foff + n].reshape(s.shape).astype(s.dtype)
            foff += n
        out.append(seg)
    return jax.tree_util.tree_unflatten(treedef, out)


def _pipeline_local_hetero(edge_params, stacked_params, x_mb, *, stage_fns,
                           axis_name, n_stages, n_micro, boundaries,
                           flen, ilen):
    """Per-shard schedule for heterogeneous stages.

    The activation crossing each stage boundary may be ANY pytree (shapes,
    dtypes and structure all free), so the ppermute'd carry is a flat
    (f32, int32) union buffer pair sized to the largest boundary; each
    shard decodes its own input struct, runs its stage via ``lax.switch``
    (a real XLA conditional — only the selected branch executes), and
    re-encodes.  Float payloads ride the f32 buffer (differentiable); int
    payloads ride the int32 buffer (exact under the integer psum).
    """
    idx = lax.axis_index(axis_name)
    stacked_local = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    n_ticks = n_micro + n_stages - 1

    def make_branch(i):
        def branch(bufs):
            act = _decode(bufs, boundaries[i])
            out = stage_fns[i](edge_params[i], stacked_local, act)
            return _encode(out, flen, ilen)
        return branch

    branches = [make_branch(i) for i in range(n_stages)]

    def tick(carry, t):
        mb = jax.tree_util.tree_map(
            lambda a: a[jnp.clip(t, 0, n_micro - 1)], x_mb)
        inj = _encode(mb, flen, ilen)
        bufs_in = jax.tree_util.tree_map(
            lambda i, c: jnp.where(idx == 0, i, c), inj, carry)
        out = lax.switch(idx, branches, bufs_in)
        shifted = jax.tree_util.tree_map(
            lambda b: lax.ppermute(b, axis_name, perm), out)
        return shifted, out

    carry0 = (jnp.zeros((flen,), jnp.float32), jnp.zeros((ilen,), jnp.int32))
    _, ys = lax.scan(tick, carry0, jnp.arange(n_ticks))
    valid = jax.tree_util.tree_map(lambda b: b[n_stages - 1:], ys)
    valid = jax.tree_util.tree_map(
        lambda b: lax.psum(
            jnp.where(idx == n_stages - 1, b, jnp.zeros_like(b)),
            axis_name),
        valid)
    return jax.vmap(lambda f, i: _decode((f, i), boundaries[n_stages]))(
        *valid)


def _infer_boundaries(stage_fns, edge_params, stacked_params, x_mb,
                      rows: int):
    """Chain jax.eval_shape through the stages to get every boundary
    struct and the (f32, int32) union-buffer sizes — shared by
    gpipe_hetero and gpipe_hetero_1f1b_grads so the two entry points can
    never diverge on what frames they encode.  ``rows``: per-shard rows
    of one microbatch (mb // dp when composing with DP)."""
    n_stages = len(stage_fns)
    stacked_local_struct = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype),
        stacked_params)
    bound = [jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct((rows,) + a.shape[2:], a.dtype),
        x_mb)]
    for i in range(n_stages):
        bound.append(jax.eval_shape(
            stage_fns[i], edge_params[i], stacked_local_struct, bound[i]))
    sizes = [_pair_sizes(s) for s in bound]
    return bound, max(f for f, _ in sizes), max(i for _, i in sizes)


def gpipe_hetero(stage_fns, edge_params, stacked_params, x, *,
                 n_microbatch, mesh=None, axis_name: str = PIPE_AXIS,
                 batch_axis: str | None = None):
    """GPipe over **non-shape-preserving** stages — embed → blocks → head
    pipelines work (VERDICT r03 weak #6: the homogeneous :func:`gpipe`
    requires one activation shape across stages).

    Args:
      stage_fns: list of S callables ``fn_i(edge_i, stacked_local, act) ->
        act'``.  Stage boundaries may change shape/dtype/pytree structure
        freely; boundary structs are inferred by chaining ``jax.eval_shape``
        from the microbatch struct.
      edge_params: list of S pytrees (or Nones) with stage-specific weights
        (embedding table, LM head, ...).  Replicated over the mesh — these
        are the small ends of the model.
      stacked_params: pytree whose leaves have leading dim S — the big
        homogeneous middle (block stacks), sharded over the pipe axis so
        HBM scales.  Stage i's slice is passed to every ``fn_i`` (pass
        ``{}`` if unused).
      x: pytree of (B, ...) arrays; the injected microbatch is the tree of
        (B/M, ...) slices.
      batch_axis: compose with DP exactly as in :func:`gpipe`.
    Returns: pytree of (B, ...) outputs with the struct of the last stage's
      output (leading dim of every output leaf must be the microbatch row
      count).
    """
    if batch_axis is not None and getattr(jax.shard_map,
                                          "_zoo_compat_04x", False):
        # fail loudly: under the jax-0.4.x shard_map shim this exact
        # combination computes WRONG numbers (outputs scaled by the
        # data-axis size — tests/test_pipeline_parallel.py
        # TestGPipeHetero::test_full_lm_with_data_parallel), and a
        # silently corrupted forward is worse than no forward
        raise NotImplementedError(
            "gpipe_hetero with a data-parallel batch_axis produces "
            "incorrect results under the jax 0.4.x shard_map compat "
            "shim; upgrade jax or drop batch_axis (run DP outside the "
            "hetero pipeline)")
    mesh = mesh or get_zoo_context().mesh
    n_stages = dict(mesh.shape).get(axis_name, 1)
    if len(stage_fns) != n_stages:
        raise ValueError(
            f"{len(stage_fns)} stage_fns != pipe axis size {n_stages}")
    b = jax.tree_util.tree_leaves(x)[0].shape[0]
    if b % n_microbatch:
        raise ValueError(f"batch {b} not divisible by M={n_microbatch}")
    mb = b // n_microbatch
    dp = dict(mesh.shape).get(batch_axis, 1) if batch_axis else 1
    if mb % dp:
        raise ValueError(f"microbatch rows {mb} not divisible by "
                         f"data shards {dp}")
    x_mb = jax.tree_util.tree_map(
        lambda a: a.reshape((n_microbatch, mb) + a.shape[1:]), x)

    # infer LOCAL per-boundary structs (rows sharded over batch_axis)
    bound, flen, ilen = _infer_boundaries(stage_fns, edge_params,
                                          stacked_params, x_mb, mb // dp)

    if n_stages == 1:
        one = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        out_mb = jax.vmap(lambda m: stage_fns[0](edge_params[0], one, m))(
            x_mb)
        return jax.tree_util.tree_map(
            lambda a: a.reshape((b,) + a.shape[2:]), out_mb)

    _record_schedule("gpipe_hetero", n_stages, n_microbatch,
                     n_stages - 1, n_microbatch + n_stages - 1)
    out = _run_planned(
        partial(_pipeline_local_hetero, stage_fns=stage_fns,
                axis_name=axis_name, n_stages=n_stages,
                n_micro=n_microbatch, boundaries=bound, flen=flen,
                ilen=ilen),
        "gpipe_hetero", mesh,
        (P(), P(axis_name), P(None, batch_axis)),
        P(None, batch_axis),
        tuple(stage_fns),
        (tuple(edge_params), stacked_params, x_mb))
    return jax.tree_util.tree_map(
        lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]), out)


# ---------------------------------------------------------------------------
# Circular / interleaved schedule (virtual stages)
# ---------------------------------------------------------------------------


def _pipeline_local_circular(stage_params, x_mb, *, stage_fn, axis_name,
                             n_stages, n_micro, repeats):
    """Interleaved ("circular") schedule: shard i hosts virtual stages
    i, i+S, ..., i+(v-1)S and the activation ring is traversed v times.
    Bubble drops from (S-1)/(M+S-1) ticks of a v-deep sequential stage to
    (S-1)/(vM+S-1) of a 1-deep stage (the Megatron interleaved-1F1B bubble
    shrink, expressed as a scan so jax.grad is still the reverse
    schedule).  Requires M >= S (round r+1 of a microbatch reaches shard 0
    M-S ticks after round r leaves shard S-1; a delay-line buffer holds
    it)."""
    idx = lax.axis_index(axis_name)
    s, m, v = n_stages, n_micro, repeats
    delay = m - s
    p_local = jax.tree_util.tree_map(lambda a: a[:, 0], stage_params)
    perm = [(j, (j + 1) % s) for j in range(s)]
    n_ticks = v * m + s - 1

    def tick(carry, t):
        ring_in, queue = carry
        if delay > 0:
            q_out = queue[t % delay]
            queue = queue.at[t % delay].set(ring_in)
        else:
            q_out = ring_in
        inj = x_mb[jnp.clip(t, 0, m - 1)]
        first_in = jnp.where(t < m, inj, q_out)
        act = jnp.where(idx == 0, first_in, ring_in)
        r = jnp.clip((t - idx) // m, 0, v - 1)
        pr = jax.tree_util.tree_map(lambda a: a[r], p_local)
        out = stage_fn(pr, act)
        shifted = lax.ppermute(out, axis_name, perm)
        return (shifted, queue), out

    queue0 = (jnp.zeros((delay,) + x_mb.shape[1:], x_mb.dtype)
              if delay > 0 else jnp.zeros((0,), x_mb.dtype))
    (_, _), ys = lax.scan(tick, (jnp.zeros_like(x_mb[0]), queue0),
                          jnp.arange(n_ticks))
    valid = ys[(v - 1) * m + s - 1:]
    return lax.psum(
        jnp.where(idx == s - 1, valid, jnp.zeros_like(valid)),
        axis_name,
    )


# ---------------------------------------------------------------------------
# 1F1B: explicit interleaved forward/backward schedule, O(S) live activations
# ---------------------------------------------------------------------------


def _pipeline_local_1f1b(stage_params, x_mb, y_mb, *, stage_fn, loss_fn,
                         axis_name, n_stages, n_micro, batch_axis):
    """Per-shard 1F1B training schedule.

    Why not ``jax.grad(gpipe)``: differentiating the scan saves every
    tick's stage output — O(M + S) live activations per stage, exactly the
    GPipe memory profile PP exists to avoid (VERDICT r4 weak #9).  Here the
    backward pipeline is written out explicitly instead: every tick runs
    one *forward slot* (stage s computes microbatch ``t - s``, activations
    hop forward on the ring) and one *backward slot* (stage s back-props
    microbatch ``t - 2S + 1 + s``, cotangents hop backward on the reversed
    ring), so microbatch m's backward reaches stage s only ``2(S - s) - 1``
    ticks after its forward.  Each stage therefore keeps just a ring
    buffer of the ≤ 2S-1 in-flight microbatches' *input* activations
    (the stage forward is recomputed inside ``jax.vjp`` at backward time —
    the same trade as ``remat``), giving a live set of O(S) activations
    independent of M.

    Schedule (0-indexed ticks, S stages, M microbatches):
      forward  of mb m at stage s: tick  m + s
      backward of mb m at stage s: tick  m + 2S - 1 - s
    Both slots are valid-masked; total ticks T = M + 2S - 2 + 1.

    The ring store is unconditional: slot ``m % 2S`` is only ever read
    between the owning microbatch's forward and backward ticks, and any
    out-of-range slot owner has provably finished its backward (in-flight
    span < 2S), so stray stores never clobber a live slot.

    Loss semantics: ``loss_fn(out_mb, y_mb) -> scalar`` (mean over the
    microbatch rows); the returned loss is the mean over microbatches and
    the grads are d(that mean)/d(stage_params).
    """
    idx = lax.axis_index(axis_name)
    s_count, m_count = n_stages, n_micro
    ring_cap = 2 * s_count
    p_local = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    fwd_perm = [(j, (j + 1) % s_count) for j in range(s_count)]
    bwd_perm = [(j, (j - 1) % s_count) for j in range(s_count)]
    n_ticks = m_count + 2 * s_count - 1
    is_last = idx == s_count - 1

    def scaled_loss(out, y):
        return loss_fn(out, y) / m_count

    def tick(carry, t):
        act_in, ct_in, ring, gacc, lacc = carry

        # ---- forward slot: stage idx advances microbatch t - idx
        mf = t - idx
        a_in = jnp.where(idx == 0, x_mb[jnp.clip(mf, 0, m_count - 1)],
                         act_in)
        ring = ring.at[mf % ring_cap].set(a_in)
        out_f = stage_fn(p_local, a_in)

        # ---- backward slot: stage idx back-props mb t - 2S + 1 + idx
        mb_ = t - 2 * s_count + 1 + idx
        b_valid = (mb_ >= 0) & (mb_ < m_count)
        a_saved = ring[mb_ % ring_cap]
        out_b, vjp = jax.vjp(stage_fn, p_local, a_saved)
        y_here = jax.tree_util.tree_map(
            lambda a: a[jnp.clip(mb_, 0, m_count - 1)], y_mb)
        l_val, ct_loss = jax.value_and_grad(scaled_loss)(out_b, y_here)
        # cotangent seed: the loss vjp at the last stage, the arriving
        # cotangent stream everywhere else
        ct_out = jnp.where(is_last, ct_loss, ct_in)
        g_p, ct_prev = vjp(ct_out)
        gacc = jax.tree_util.tree_map(
            lambda g, d: g + jnp.where(b_valid, d, jnp.zeros_like(d)),
            gacc, g_p)
        lacc = lacc + jnp.where(is_last & b_valid, l_val, 0.0)

        act_next = lax.ppermute(out_f, axis_name, fwd_perm)
        ct_next = lax.ppermute(
            jnp.where(b_valid, ct_prev, jnp.zeros_like(ct_prev)),
            axis_name, bwd_perm)
        return (act_next, ct_next, ring, gacc, lacc), None

    act0 = jnp.zeros_like(x_mb[0])
    ring0 = jnp.zeros((ring_cap,) + x_mb.shape[1:], x_mb.dtype)
    gacc0 = jax.tree_util.tree_map(jnp.zeros_like, p_local)
    (_, _, _, gacc, lacc), _ = lax.scan(
        tick, (act0, jnp.zeros_like(act0), ring0, gacc0,
               jnp.zeros((), jnp.float32)),
        jnp.arange(n_ticks))
    loss = lax.psum(lacc, axis_name)  # only the last stage accumulated
    if batch_axis is not None:
        # DP composition: rows are sharded over batch_axis, so local
        # means/grad-sums average across the data shards
        loss = lax.pmean(loss, batch_axis)
        gacc = jax.tree_util.tree_map(
            lambda g: lax.pmean(g, batch_axis), gacc)
    # re-add the stage leading dim so out_specs P(axis_name) reassembles
    # the global (S, ...) grad pytree
    return loss, jax.tree_util.tree_map(lambda g: g[None], gacc)


def gpipe_1f1b_grads(stage_fn, loss_fn, stage_params, x, y, *,
                     n_microbatch, mesh=None, axis_name: str = PIPE_AXIS,
                     batch_axis: str | None = None):
    """Loss and gradients of a pipelined stage stack under the **1F1B**
    memory schedule: per-stage live activations are O(S) (the in-flight
    window), not O(M) as with ``jax.grad(gpipe)`` — the schedule that
    makes pipeline parallelism actually save memory at the model sizes it
    exists for.  ``tests/test_pipeline_parallel.py`` asserts the compiled
    temp-buffer footprint stays flat in M while the GPipe one grows.

    Args:
      stage_fn: ``(params_one_stage, act) -> act`` (shape-preserving, the
        :func:`gpipe` contract).
      loss_fn: ``(final_act_mb, y_mb) -> scalar`` mean loss over one
        microbatch's rows.
      stage_params: leaves with leading dim S (pipe-sharded under jit).
      x, y: (B, ...) batch and labels; B % n_microbatch == 0.
      batch_axis: compose with DP exactly as in :func:`gpipe` (grads are
        pmean'd over the data axis inside the schedule).
    Returns:
      ``(loss, grads)`` — loss replicated, grads matching ``stage_params``
      (leading dim S, pipe-sharded).
    """
    mesh = mesh or get_zoo_context().mesh
    n_stages = dict(mesh.shape).get(axis_name, 1)
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != pipe axis "
                f"size {n_stages} (leaf shape {leaf.shape})")
    b = x.shape[0]
    if b % n_microbatch:
        raise ValueError(f"batch {b} not divisible by M={n_microbatch}")
    mb_rows = b // n_microbatch
    x_mb = x.reshape((n_microbatch, mb_rows) + x.shape[1:])
    y_mb = jax.tree_util.tree_map(
        lambda a: a.reshape((n_microbatch, mb_rows) + a.shape[1:]), y)

    if n_stages == 1:
        # validation above pinned the leading dim to 1: one stage, applied
        # directly (no pipeline)
        def whole(sp):
            one = jax.tree_util.tree_map(lambda a: a[0], sp)
            out = stage_fn(one, x)
            om = out.reshape((n_microbatch, mb_rows) + out.shape[1:])
            per = jax.vmap(loss_fn)(om, y_mb)
            return jnp.mean(per)

        return jax.value_and_grad(whole)(stage_params)

    # dual fwd/bwd schedule runs T = M + 2S - 1 ticks with M useful
    # slots per stream per stage, so each stream idles T - M = 2S - 1
    # ticks (fill + drain + the one-tick fwd->bwd offset at the last
    # stage)
    _record_schedule("1f1b", n_stages, n_microbatch,
                     2 * n_stages - 1, n_microbatch + 2 * n_stages - 1)
    return _run_planned(
        partial(_pipeline_local_1f1b, stage_fn=stage_fn, loss_fn=loss_fn,
                axis_name=axis_name, n_stages=n_stages,
                n_micro=n_microbatch, batch_axis=batch_axis),
        "1f1b", mesh,
        (P(axis_name), P(None, batch_axis), P(None, batch_axis)),
        (P(), P(axis_name)),
        (stage_fn, loss_fn),
        (stage_params, x_mb, y_mb))


def _pipeline_local_1f1b_hetero(edge_params, stacked_params, x_mb, y_mb,
                                *, stage_fns, loss_fn, axis_name,
                                n_stages, n_micro, boundaries, out_struct,
                                flen, ilen):
    """Per-shard 1F1B over HETEROGENEOUS stages — the same dual-slot
    schedule as :func:`_pipeline_local_1f1b` (see its docstring for the
    tick math and the ring-store safety argument) over the union-buffer
    carry of :func:`_pipeline_local_hetero`: activations travel as a
    (f32, int32) frame pair, each stage decodes/encodes its own boundary
    struct inside a ``lax.switch``.

    Backward specifics of the encoded carry: only the FLOAT buffer
    carries gradient (the int payload — token ids — is forward-only), so
    the cotangent ring is fbuf-shaped and ``jax.vjp`` is taken with the
    saved int frame closed over.  Parameter cotangents: every shard's
    ``lax.switch`` vjp yields zeros for the branches it didn't run, so a
    ``psum`` over the pipe axis assembles the full edge-param gradients
    (replicated), while the stacked (stage-sharded) gradients stay
    local."""
    idx = lax.axis_index(axis_name)
    s_count, m_count = n_stages, n_micro
    ring_cap = 2 * s_count
    stacked_local = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
    fwd_perm = [(j, (j + 1) % s_count) for j in range(s_count)]
    bwd_perm = [(j, (j - 1) % s_count) for j in range(s_count)]
    n_ticks = m_count + 2 * s_count - 1
    is_last = idx == s_count - 1

    def stage_apply(edge, stacked_l, fbuf, ibuf):
        def make_branch(i):
            def branch(args):
                e, sl, fb, ib = args
                act = _decode((fb, ib), boundaries[i])
                out = stage_fns[i](e[i], sl, act)
                return _encode(out, flen, ilen)
            return branch

        return lax.switch(idx, [make_branch(i) for i in range(s_count)],
                          (edge, stacked_l, fbuf, ibuf))

    def scaled_loss(out_bufs, y):
        out = _decode(out_bufs, out_struct)
        return loss_fn(out, y) / m_count

    def tick(carry, t):
        (act_f, act_i), ct_in, (ring_f, ring_i), gacc, lacc = carry

        # ---- forward slot: stage idx advances microbatch t - idx.
        # Encode the injected microbatch HERE, from the raw (token-sized)
        # input: pre-encoding all M frames would stage M copies padded to
        # the LARGEST boundary (the logits frame for an LM) — O(M·flen)
        # replicated per shard, eroding the O(S) live set this schedule
        # exists to provide.
        mf = t - idx
        inj_f, inj_i = _encode(jax.tree_util.tree_map(
            lambda a: a[jnp.clip(mf, 0, m_count - 1)], x_mb), flen, ilen)
        a_f = jnp.where(idx == 0, inj_f, act_f)
        a_i = jnp.where(idx == 0, inj_i, act_i)
        ring_f = ring_f.at[mf % ring_cap].set(a_f)
        ring_i = ring_i.at[mf % ring_cap].set(a_i)
        out_f = stage_apply(edge_params, stacked_local, a_f, a_i)

        # ---- backward slot: stage idx back-props mb t - 2S + 1 + idx
        mb_ = t - 2 * s_count + 1 + idx
        b_valid = (mb_ >= 0) & (mb_ < m_count)
        saved_f = ring_f[mb_ % ring_cap]
        saved_i = ring_i[mb_ % ring_cap]
        (out_bf, out_bi), vjp = jax.vjp(
            lambda e, sl, fb: stage_apply(e, sl, fb, saved_i),
            edge_params, stacked_local, saved_f)
        y_here = jax.tree_util.tree_map(
            lambda a: a[jnp.clip(mb_, 0, m_count - 1)], y_mb)
        l_val, ct_loss = jax.value_and_grad(
            lambda fb: scaled_loss((fb, out_bi), y_here))(out_bf)
        ct_out = jnp.where(is_last, ct_loss, ct_in)
        # integer outputs take float0 cotangents (not int zeros)
        import numpy as _np

        ct_i = _np.zeros(out_bi.shape, jax.dtypes.float0)
        g_edge, g_stacked, ct_prev = vjp((ct_out, ct_i))
        gacc = jax.tree_util.tree_map(
            lambda g, d: g + jnp.where(b_valid, d, jnp.zeros_like(d)),
            gacc, (g_edge, g_stacked))
        lacc = lacc + jnp.where(is_last & b_valid, l_val, 0.0)

        act_next = tuple(lax.ppermute(a, axis_name, fwd_perm)
                         for a in out_f)
        ct_next = lax.ppermute(
            jnp.where(b_valid, ct_prev, jnp.zeros_like(ct_prev)),
            axis_name, bwd_perm)
        return (act_next, ct_next, (ring_f, ring_i), gacc, lacc), None

    act0 = (jnp.zeros((flen,), jnp.float32), jnp.zeros((ilen,), jnp.int32))
    ring0 = (jnp.zeros((ring_cap, flen), jnp.float32),
             jnp.zeros((ring_cap, ilen), jnp.int32))
    gacc0 = jax.tree_util.tree_map(
        jnp.zeros_like, (edge_params, stacked_local))
    (_, _, _, (g_edge, g_stacked), lacc), _ = lax.scan(
        tick, (act0, jnp.zeros((flen,), jnp.float32), ring0, gacc0,
               jnp.zeros((), jnp.float32)),
        jnp.arange(n_ticks))
    loss = lax.psum(lacc, axis_name)
    # each shard holds cotangents only for ITS branch; assemble
    g_edge = jax.tree_util.tree_map(
        lambda g: lax.psum(g, axis_name), g_edge)
    return loss, g_edge, jax.tree_util.tree_map(
        lambda g: g[None], g_stacked)


def gpipe_hetero_1f1b_grads(stage_fns, edge_params, stacked_params, x, y,
                            loss_fn, *, n_microbatch, mesh=None,
                            axis_name: str = PIPE_AXIS):
    """Loss and gradients of a HETEROGENEOUS pipeline (the
    :func:`gpipe_hetero` stage contract: embed → blocks → head with
    free-form boundaries) under the 1F1B memory schedule — O(S) live
    activation frames per stage instead of ``jax.grad(gpipe_hetero)``'s
    O(M) saved tick outputs.  This is 1F1B at exactly the model shape PP
    exists for: the full LM whose ends change activation shape.

    Args follow :func:`gpipe_hetero` (stage_fns, edge_params,
    stacked_params, x) plus ``loss_fn(final_act_mb, y_mb) -> scalar``
    (mean over one microbatch's rows; the returned loss is the mean over
    microbatches).  Unlike ``gpipe_hetero`` there is NO ``batch_axis``
    yet: PP x DP composition of the hetero 1F1B schedule would need
    per-data-shard frame encoding — run it on a pipe-only mesh (the
    homogeneous :func:`gpipe_1f1b_grads` does compose with DP).

    Returns ``(loss, edge_grads, stacked_grads)`` — loss and edge grads
    replicated, stacked grads with leading dim S (pipe-sharded).
    """
    mesh = mesh or get_zoo_context().mesh
    n_stages = dict(mesh.shape).get(axis_name, 1)
    if len(stage_fns) != n_stages:
        raise ValueError(
            f"{len(stage_fns)} stage_fns != pipe axis size {n_stages}")
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stacked_params leading dim {leaf.shape[0]} != pipe "
                f"axis size {n_stages} (leaf shape {leaf.shape}); for "
                "multiple blocks per stage use a (S, per, ...) layout "
                "with the blocks folded inside the stage fn")
    b = jax.tree_util.tree_leaves(x)[0].shape[0]
    if b % n_microbatch:
        raise ValueError(f"batch {b} not divisible by M={n_microbatch}")
    mb = b // n_microbatch
    x_mb = jax.tree_util.tree_map(
        lambda a: a.reshape((n_microbatch, mb) + a.shape[1:]), x)
    y_mb = jax.tree_util.tree_map(
        lambda a: a.reshape((n_microbatch, mb) + a.shape[1:]), y)

    if n_stages == 1:
        # no pipe axis: one vmapped stage body under value_and_grad (an
        # unrolled python loop would trace M stage copies)
        def whole(params):
            e, sl_stacked = params
            sl = jax.tree_util.tree_map(lambda a: a[0], sl_stacked)
            per = jax.vmap(
                lambda xm, ym: loss_fn(stage_fns[0](e[0], sl, xm), ym)
            )(x_mb, y_mb)
            return jnp.mean(per)

        loss, (g_edge, g_stacked) = jax.value_and_grad(whole)(
            (tuple(edge_params), stacked_params))
        return loss, g_edge, g_stacked

    bound, flen, ilen = _infer_boundaries(stage_fns, edge_params,
                                          stacked_params, x_mb, mb)

    _record_schedule("1f1b_hetero", n_stages, n_microbatch,
                     2 * n_stages - 1, n_microbatch + 2 * n_stages - 1)
    return _run_planned(
        partial(_pipeline_local_1f1b_hetero, stage_fns=stage_fns,
                loss_fn=loss_fn, axis_name=axis_name, n_stages=n_stages,
                n_micro=n_microbatch, boundaries=bound,
                out_struct=bound[n_stages], flen=flen, ilen=ilen),
        "1f1b_hetero", mesh,
        (P(), P(axis_name), P(), P()),
        (P(), P(), P(axis_name)),
        (tuple(stage_fns), loss_fn),
        (tuple(edge_params), stacked_params, x_mb, y_mb))


def stack_stage_params(per_stage: list):
    """Stack a list of identically-structured per-stage param pytrees into
    the leading-stage-dim layout ``gpipe`` expects."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage
    )


def transformer_gpipe_lm(layer, params, head_kernel, head_bias, tokens, *,
                         n_microbatch, mesh=None,
                         axis_name: str = PIPE_AXIS,
                         batch_axis: str | None = None):
    """A FULL GPT-style LM pipelined end-to-end — token embedding on stage
    0, the block stack spread over all stages, the LM head on the last
    stage — i.e. the embed → blocks → head split whose changing activation
    shapes ((B, L) int32 → (B, L, D) → (B, L, V)) the homogeneous
    :func:`gpipe` cannot express (VERDICT r03 weak #6).  Built on
    :func:`gpipe_hetero`: embeddings/head ride as replicated edge params
    (the small ends), the blocks are pipe-sharded stacked params.

    Args:
      layer: a built ``TransformerLayer`` (``layer.n_block`` must divide
        the pipe axis size evenly).
      params: the layer's param pytree (``tok_embed``/``pos_embed``/
        ``blocks``).
      head_kernel, head_bias: the LM head (D, V)/(V,).
      tokens: (B, L) int32.
    Returns: (B, L, V) logits.  Blocks run inference-mode (dropout off);
    ``layer.remat=True`` is honored per stage.
    """
    if getattr(layer, "moe_experts", 0):
        raise ValueError(
            "pipeline stage builders carry dense blocks only: an MoE "
            "stack's load-balancing aux loss cannot ride the microbatch "
            "schedule and would be silently dropped (train MoE with the "
            "GSPMD estimator step / dryrun phase 6 path instead)")
    mesh = mesh or get_zoo_context().mesh
    n_stages = dict(mesh.shape).get(axis_name, 1)
    blocks = params["blocks"] if isinstance(params, dict) else params
    n_block = len(blocks)
    if n_block % n_stages:
        raise ValueError(f"n_block {n_block} not divisible by pipe size "
                         f"{n_stages}")
    per = n_block // n_stages
    # stack into (S, per, ...) leaves: stage i holds blocks[i*per:(i+1)*per]
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves).reshape(
            (n_stages, per) + leaves[0].shape), *list(blocks))

    def run_blocks(stacked_local, h):
        from analytics_zoo_tpu.parallel.plan import (
            apply_remat,
            resolve_remat,
        )

        policy = resolve_remat("blocks", default=layer.remat)
        body = apply_remat(layer._block_forward, policy,
                           static_argnums=(3,))
        for j in range(per):
            bp = jax.tree_util.tree_map(lambda a, _j=j: a[_j],
                                        stacked_local)
            h = body(bp, h, None, False, None)
        return h

    def first_fn(edge, stacked_local, toks):
        l = toks.shape[-1]
        h = jnp.take(edge["tok"], toks.astype(jnp.int32), axis=0)
        h = h + edge["pos"][:l]
        return run_blocks(stacked_local, h)

    def mid_fn(edge, stacked_local, h):
        return run_blocks(stacked_local, h)

    def last_fn(edge, stacked_local, h):
        h = run_blocks(stacked_local, h)
        return h @ edge["w"] + edge["b"]

    edge = [None] * n_stages
    edge[0] = {"tok": params["tok_embed"], "pos": params["pos_embed"]}
    last_edge = {"w": head_kernel, "b": head_bias}
    if n_stages == 1:
        edge[0] = {**edge[0], **last_edge}

        def only_fn(e, sl, toks):
            h = first_fn(e, sl, toks)
            return h @ e["w"] + e["b"]

        fns = [only_fn]
    else:
        edge[-1] = last_edge
        fns = ([first_fn] + [mid_fn] * (n_stages - 2) + [last_fn])
    return gpipe_hetero(fns, edge, stacked, tokens,
                        n_microbatch=n_microbatch, mesh=mesh,
                        axis_name=axis_name, batch_axis=batch_axis)


def transformer_gpipe(layer, params, h, *, n_microbatch, mask=None,
                      mesh=None, axis_name: str = PIPE_AXIS,
                      batch_axis=None):
    """Run a transformer block stack (TransformerLayer/BERT core) as a
    GPipe pipeline: block i's weights live on pipe shard i.

    ``layer.n_block`` must equal the pipe axis size; ``h`` is the
    post-embedding activation (B, L, D) — embeddings and the head stay
    replicated (they are the small ends of the model; the block stack is
    what outgrows one chip's HBM).  ``mask`` is an additive attention mask
    closed over every stage; because the schedule re-slices the batch into
    microbatches, only batch-independent masks are expressible (shape
    (L, L) or (1, 1, L, L) — shared structural masks).  Per-sample padding
    masks (leading batch dim > 1, the BERT padded-batch case) are
    rejected: they cannot follow the microbatch slicing through a closure.
    Blocks run in inference mode (dropout off); the scan+ppermute schedule
    is shared with :func:`gpipe`, so jax.grad still yields the reverse
    pipeline for training use, and ``layer.remat=True`` is honored per
    stage.
    """
    if getattr(layer, "moe_experts", 0):
        raise ValueError(
            "pipeline stage builders carry dense blocks only: an MoE "
            "stack's load-balancing aux loss cannot ride the microbatch "
            "schedule and would be silently dropped (train MoE with the "
            "GSPMD estimator step / dryrun phase 6 path instead)")
    if mask is not None and mask.ndim >= 3 and mask.shape[0] != 1:
        raise ValueError(
            "transformer_gpipe: per-sample masks (leading batch dim "
            f"{mask.shape[0]}) cannot follow the microbatch schedule; "
            "only batch-independent masks are supported")
    blocks = params["blocks"] if isinstance(params, dict) else params
    stacked = stack_stage_params(list(blocks))

    from analytics_zoo_tpu.parallel.plan import apply_remat, resolve_remat

    def block_fn(bp, act):
        return layer._block_forward(bp, act, mask, False, None)

    def stage_fn(bp, act):
        # resolved INSIDE the stage body, i.e. at trace time, so a
        # remat_rules entry on the plan being compiled wins over the
        # layer flag
        policy = resolve_remat("blocks", default=layer.remat)
        return apply_remat(block_fn, policy)(bp, act)

    return gpipe(stage_fn, stacked, h, n_microbatch=n_microbatch,
                 mesh=mesh, axis_name=axis_name, batch_axis=batch_axis)
