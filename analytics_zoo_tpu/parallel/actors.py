"""Generic distributed-Python actors — the RayOnSpark capability rebuilt
for TPU-VM pods.

Reference: ``RayContext`` launches a Ray cluster inside Spark executors
(pyzoo/zoo/ray/util/raycontext.py:192-393, barrier-mode ``ray start`` +
JVMGuard pid reaping) so users can run arbitrary distributed Python
(parameter servers, RL) beside their training jobs.  On a TPU-VM pod the
SPMD fabric is jax.distributed (parallel/multihost.py); what this module
adds is the reference's OTHER capability: **actor-style arbitrary-Python
compute** with a Ray-shaped API, scheduled onto local processes (one per
actor, the analogue of raylets on the executor hosts):

* ``ActorContext.init()`` ≈ RayContext.init — start the runtime;
* ``@remote`` on a class ≈ ``@ray.remote`` — ``Cls.remote(...)`` spawns
  the actor in its own process; ``actor.method.remote(...)`` returns an
  :class:`ObjectRef`; ``get(ref_or_list)`` materializes results;
* ``@remote`` on a function — runs on a shared process pool;
* actors die with the parent (daemon processes — the JVMGuard role of
  raycontext.py:32-50).

Calls to one actor execute in order (the actor model); calls to different
actors run concurrently.  Method args/results travel by pickle, so keep
them arrays/pytrees (the plasma-store role is played by the OS pipe —
right-sized for the parameter-server/RL patterns the reference ships as
examples, not for shuffling datasets).

Actors START BY SPAWN, not fork: the intended use is rollout workers and
parameter servers living NEXT TO a JAX training process, and forking a
process whose XLA runtime already started threads risks deadlock in the
child (CPython 3.12+ warns on every such fork; VERDICT r4 weak #8).  The
actor payload (class + init args) ships to the fresh interpreter via
cloudpickle, so nested/locally-defined actor classes still work; remote
*functions* run on a spawn process pool and must stay module-level
(resolved by import path in the worker).
"""

from __future__ import annotations

import multiprocessing as mp
import threading
import traceback
from typing import Any

_CONTEXT: "ActorContext | None" = None

# Reserved control method (ISSUE 2): a call frame whose method slot is
# this name never reaches the user object — the actor process answers
# with its own telemetry snapshot (metrics/merge.py format: registry +
# health), so the driver can pull per-actor metrics over the SAME
# ordered channel user calls travel on (no second socket, the HMAC
# handshake and framing are reused unchanged on the TCP path).
TELEMETRY_METHOD = "__zoo_telemetry__"


class ActorError(RuntimeError):
    """An exception raised inside an actor, re-raised at ``get``."""


def _actor_loop(payload, conn):
    try:
        import cloudpickle

        cls, args, kwargs = cloudpickle.loads(payload)
        obj = cls(*args, **kwargs)
        conn.send(("ready", None))
    except BaseException:
        conn.send(("init_error", traceback.format_exc()))
        return
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            return  # driver/worker gone: quiet exit (daemon teardown)
        if msg is None:  # shutdown
            return
        call_id, method, m_args, m_kwargs = msg
        try:
            if method == TELEMETRY_METHOD:
                from analytics_zoo_tpu.metrics.merge import (
                    telemetry_snapshot,
                )

                result = telemetry_snapshot()
            else:
                result = getattr(obj, method)(*m_args, **m_kwargs)
            conn.send((call_id, "ok", result))
        except BaseException:
            conn.send((call_id, "error", traceback.format_exc()))


class ObjectRef:
    """Future for one actor method call (the ray.ObjectRef role).

    ``get`` may be called repeatedly and from multiple threads: the first
    successful wait caches the outcome on the ref, later calls return it
    without touching the pipe."""

    def __init__(self, actor: "ActorHandle", call_id: int):
        self._actor = actor
        self._call_id = call_id
        self._lock = threading.Lock()
        self._done = False
        self._outcome: tuple[str, Any] | None = None

    def get(self, timeout: float | None = None):
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        remaining = -1 if timeout is None else timeout
        if not self._lock.acquire(timeout=remaining):
            raise TimeoutError(f"call {self._call_id} timed out")
        try:
            if not self._done:
                remaining = None if deadline is None \
                    else max(deadline - _time.monotonic(), 0.0)
                value = self._actor._wait_for(self._call_id, remaining)
                self._outcome = ("ok", value)
                self._done = True
        except ActorError as e:
            self._outcome = ("error", e)
            self._done = True
        finally:
            self._lock.release()
        status, payload = self._outcome
        if status == "error":
            raise payload
        return payload


class _RemoteMethod:
    def __init__(self, actor, name):
        self._actor = actor
        self._name = name

    def remote(self, *args, **kwargs) -> ObjectRef:
        return self._actor._call(self._name, args, kwargs)


class ActorHandle:
    """Client-side handle; one process per actor.

    Thread-safe: sends serialize on a send lock (so concurrent
    ``.remote()`` calls never interleave pipe writes or block behind an
    in-flight ``get``); one waiter at a time drains the pipe under a recv
    lock while others sleep on a condition variable, and ``get(timeout)``
    is a TOTAL deadline, not per-message."""

    def __init__(self, cls, args, kwargs, ctx, worker: str | None = None,
                 secret=None):
        import cloudpickle

        self._ctx = ctx
        self._cls_name = cls.__name__
        self._worker = worker
        self._closed = False
        # cloudpickle-by-value: the spawned interpreter has no import path
        # to nested/test-local classes, and module-level ones are shadowed
        # by the @remote wrapper anyway
        payload = cloudpickle.dumps((cls, args, kwargs))
        if worker is not None:
            # cross-host placement: the actor lives on the worker server's
            # host; this handle holds one TCP conn (ordering = TCP order)
            from analytics_zoo_tpu.parallel.actor_worker import (
                connect_and_spawn,
            )

            self._conn = connect_and_spawn(worker, payload,
                                           secret=secret)
            self._proc = None
        else:
            spawn = mp.get_context("spawn")  # fork-unsafe next to JAX
            parent, child = spawn.Pipe()
            self._conn = parent
            self._proc = spawn.Process(
                target=_actor_loop, args=(payload, child),
                daemon=True)  # daemon: dies with the parent (JVMGuard)
            self._proc.start()
        import weakref

        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        self._cv = threading.Condition()
        self._next_id = 0  # guarded-by: _send_lock
        self._results: dict[int, tuple[str, Any]] = {}  # guarded-by: _cv
        # live refs by call id: replies whose ref was never created or has
        # been dropped (fire-and-forget .remote()) are discarded instead of
        # accumulating in _results forever
        self._refs = weakref.WeakValueDictionary()  # guarded-by: _send_lock
        status, detail = self._conn.recv()
        if status != "ready":
            raise ActorError(f"actor {cls.__name__} failed to start:\n"
                             f"{detail}")
        ctx._actors.append(self)
        # health model (metrics/health.py): an actor connection is
        # idle-OK but break-FAIL — explicit verdict, not a heartbeat age
        self._health_name = (
            f"actor:{self._cls_name}-{len(ctx._actors) - 1}")
        self._set_health(True)

    def _set_health(self, ok: bool):
        try:
            from analytics_zoo_tpu.metrics.health import get_health

            get_health().set_status(self._health_name, ok)
        except Exception:
            pass  # telemetry must never take an actor call down

    def _drop_health(self):
        try:
            from analytics_zoo_tpu.metrics.health import get_health

            get_health().unregister(self._health_name)
        except Exception:
            pass

    def _call(self, method, args, kwargs) -> ObjectRef:
        with self._send_lock:
            call_id = self._next_id
            self._next_id += 1
            # register the ref BEFORE the request leaves: otherwise a fast
            # reply drained by a concurrent reader sees no live ref and
            # discards the result this caller is about to wait on
            ref = ObjectRef(self, call_id)
            self._refs[call_id] = ref
            self._conn.send((call_id, method, args, kwargs))
        return ref

    def _take(self, call_id):
        # zoolint: disable=guarded-by -- every _take call site holds _cv (the whole-program pass proves it); runtime-checked under ZOO_SAN
        status, payload = self._results.pop(call_id)
        if status == "error":
            raise ActorError(payload)
        return payload

    def _wait_for(self, call_id, timeout=None):
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            with self._cv:
                if call_id in self._results:
                    return self._take(call_id)
            remaining = None if deadline is None \
                else deadline - _time.monotonic()
            if remaining is not None and remaining <= 0:
                raise TimeoutError(f"call {call_id} timed out")
            if self._recv_lock.acquire(blocking=False):
                try:
                    # became the reader; re-check first (a prior reader may
                    # have delivered our result between checks)
                    with self._cv:
                        if call_id in self._results:
                            return self._take(call_id)
                    if remaining is not None and \
                            not self._conn.poll(remaining):
                        raise TimeoutError(f"call {call_id} timed out")
                    try:
                        got_id, status, payload = self._conn.recv()
                    except (EOFError, OSError):
                        # the actor process / socket died mid-call:
                        # surface it in /healthz before re-raising
                        self._set_health(False)
                        raise
                    with self._cv:
                        # drop replies nobody holds a ref to (the
                        # fire-and-forget pattern), and purge stored
                        # results whose ref has since been dropped without
                        # get() — _results stays bounded by LIVE refs
                        if got_id == call_id or got_id in self._refs:
                            self._results[got_id] = (status, payload)
                        for stale in [i for i in self._results
                                      if i != call_id
                                      and i not in self._refs]:
                            del self._results[stale]
                        self._cv.notify_all()
                finally:
                    self._recv_lock.release()
            else:
                # another thread is reading; sleep until it posts a result
                with self._cv:
                    if call_id in self._results:
                        return self._take(call_id)
                    self._cv.wait(timeout=0.05 if remaining is None
                                  else min(0.05, remaining))

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _RemoteMethod(self, name)

    def telemetry(self, timeout: float | None = 30.0) -> dict:
        """Pull this actor process's telemetry snapshot (registry +
        health, metrics/merge.py format) over the reserved
        ``__zoo_telemetry__`` frame — same ordered channel as user
        calls, so the snapshot reflects every call completed before it.
        """
        return self._call(TELEMETRY_METHOD, (), {}).get(timeout)

    def terminate(self):
        self._closed = True  # metrics() pulls skip a shut-down actor
        self._drop_health()  # a DELIBERATE shutdown is not a failure
        try:
            self._conn.send(None)
            if self._proc is not None:
                self._proc.join(timeout=5)
        except (BrokenPipeError, OSError, EOFError):
            pass
        if self._proc is not None and self._proc.is_alive():
            self._proc.terminate()
        close = getattr(self._conn, "close", None)
        if close:
            close()


class _RemoteClass:
    def __init__(self, cls, worker=None, secret=None):
        self._cls = cls
        self._worker = worker
        self._secret = secret

    _UNSET = object()

    def options(self, worker=_UNSET, secret=_UNSET) -> "_RemoteClass":
        """Placement options (the ``.options()`` surface of ray):
        ``worker`` is a registered worker address ("host:port"), an index
        into ``ActorContext.init(workers=[...])``, or None (local);
        ``secret`` is the worker server's shared auth secret for drivers
        that cannot set ZOO_ACTOR_SECRET (actor_worker.py handshake).
        Omitted fields carry over from this instance, so chained
        ``.options(worker=...).options(secret=...)`` calls compose."""
        u = _RemoteClass._UNSET
        return _RemoteClass(
            self._cls,
            worker=self._worker if worker is u else worker,
            secret=self._secret if secret is u else secret)

    def remote(self, *args, **kwargs) -> ActorHandle:
        ctx = ActorContext.current()
        return ActorHandle(self._cls, args, kwargs, ctx,
                           worker=ctx._resolve_worker(self._worker),
                           secret=self._secret)

    def __call__(self, *args, **kwargs):
        return self._cls(*args, **kwargs)  # local construction still works


class _FnRef:
    def __init__(self, future):
        self._future = future

    def get(self, timeout=None):
        return self._future.result(timeout)


def _resolve_and_call(module_name, qualname, args, kwargs):
    """Pool-side trampoline: the @remote wrapper shadows the function's
    module-level name, so pickling the inner function by reference fails —
    resolve the (possibly wrapped) attribute in the child instead."""
    import importlib

    obj = importlib.import_module(module_name)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    if isinstance(obj, _RemoteFunction):
        obj = obj._fn
    return obj(*args, **kwargs)


class _RemoteFunction:
    def __init__(self, fn):
        self._fn = fn

    def remote(self, *args, **kwargs) -> _FnRef:
        ctx = ActorContext.current()
        return _FnRef(ctx._pool.submit(
            _resolve_and_call, self._fn.__module__, self._fn.__qualname__,
            args, kwargs))

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def remote(cls_or_fn):
    """``@remote`` on a class or function (the ``@ray.remote`` surface).

    Functions/classes must be MODULE-LEVEL (importable by qualified name
    in the worker process) — nested functions, lambdas and methods are
    rejected up front instead of failing obscurely in the pool child."""
    if isinstance(cls_or_fn, type):
        # classes travel to the spawned child by cloudpickle value (no
        # import-path resolution), so nested classes are fine
        return _RemoteClass(cls_or_fn)
    qn = getattr(cls_or_fn, "__qualname__", "")
    if "<locals>" in qn or "<lambda>" in qn:
        raise ValueError(
            f"@remote target {qn!r} is not module-level; pool workers "
            "resolve remote FUNCTIONS by import path, so define it at "
            "module scope (classes may be nested)")
    return _RemoteFunction(cls_or_fn)


def get(refs, timeout: float | None = None):
    """Materialize one ref or a list of refs (the ``ray.get`` surface)."""
    if isinstance(refs, (list, tuple)):
        return type(refs)(r.get(timeout) for r in refs)
    return refs.get(timeout)


class ActorContext:
    """Runtime holder (the RayContext.init/stop surface)."""

    def __init__(self, num_pool_workers: int = 2, workers=None):
        from concurrent.futures import ProcessPoolExecutor

        self._actors: list[ActorHandle] = []
        # cross-host worker servers ("host:port") — actor_worker.py; an
        # actor with no explicit placement round-robins over them when
        # any are registered, else spawns locally
        self._workers: list[str] = list(workers or [])
        self._rr = 0
        self._pool = ProcessPoolExecutor(
            max_workers=num_pool_workers,
            mp_context=mp.get_context("spawn"))

    def _resolve_worker(self, worker) -> str | None:
        if worker is None:
            if not self._workers:
                return None
            addr = self._workers[self._rr % len(self._workers)]
            self._rr += 1
            return addr
        if isinstance(worker, int):
            if not 0 <= worker < len(self._workers):
                raise ValueError(
                    f"worker index {worker} out of range: "
                    f"{len(self._workers)} worker server(s) registered "
                    "(ActorContext.init(workers=['host:port', ...]))")
            return self._workers[worker]
        if worker == "local":
            return None
        return str(worker)

    @classmethod
    def init(cls, num_pool_workers: int = 2,
             workers=None) -> "ActorContext":
        """Start the runtime (≈ RayContext.init).  ``workers``: list of
        ``"host:port"`` actor worker servers (one per pod host, started
        with ``python -m analytics_zoo_tpu.parallel.actor_worker``) —
        actors then place across hosts, round-robin by default."""
        global _CONTEXT
        if _CONTEXT is None:
            _CONTEXT = cls(num_pool_workers, workers=workers)
        elif workers:
            _CONTEXT._workers = list(workers)
        return _CONTEXT

    @classmethod
    def current(cls) -> "ActorContext":
        if _CONTEXT is None:
            return cls.init()
        return _CONTEXT

    def metrics(self, timeout: float | None = 30.0,
                aggregator=None) -> dict:
        """Pod-level telemetry pull (ISSUE 2): one ``__zoo_telemetry__``
        round-trip per live actor plus one per registered worker server,
        folded into a :class:`~analytics_zoo_tpu.metrics.merge.
        TelemetryAggregator` — actor series labeled ``actor=<Cls-i>``,
        worker-server series ``host=<addr>`` — and returned as its
        ``merged()`` doc (per-source series, cluster totals, the driver
        registry alongside).  Unreachable sources are skipped and listed
        under ``"errors"``: a metrics pull must never raise because one
        actor died.  Pass ``aggregator=`` to fold into an existing one
        (e.g. the one a :class:`MetricsServer` is serving)."""
        from concurrent.futures import ThreadPoolExecutor

        from analytics_zoo_tpu.metrics.merge import TelemetryAggregator
        from analytics_zoo_tpu.parallel.actor_worker import (
            fetch_worker_telemetry,
        )

        agg = aggregator if aggregator is not None else TelemetryAggregator()
        # one pull job per source: (error key, source labels, fetch fn)
        jobs = []
        for i, a in enumerate(self._actors):
            if a._closed:
                continue  # deliberately terminated: not an error source
            source = {"actor": f"{a._cls_name}-{i}"}
            if a._worker is not None:
                source["host"] = a._worker
            jobs.append((f"actor:{a._cls_name}-{i}", source,
                         lambda a=a: a.telemetry(timeout)))
        for addr in self._workers:
            jobs.append((f"worker:{addr}", {"host": addr},
                         lambda addr=addr: fetch_worker_telemetry(
                             addr, timeout=timeout)))
        errors = {}
        if jobs:
            # concurrent pulls: one wedged source costs max(RTT), not
            # sum(RTT) — a scrape loop over a 16-actor pod with one dead
            # host must not stall 16 x timeout
            with ThreadPoolExecutor(
                    max_workers=min(16, len(jobs)),
                    thread_name_prefix="zoo-telemetry-pull") as pool:
                futures = [(key, labels, pool.submit(fn))
                           for key, labels, fn in jobs]
                for key, labels, fut in futures:
                    try:
                        agg.ingest(fut.result(), **labels)
                    except Exception as e:
                        errors[key] = repr(e)
        doc = agg.merged()
        if errors:
            doc["errors"] = errors
        return doc

    def stop(self):
        global _CONTEXT
        for a in self._actors:
            a.terminate()
        self._actors.clear()
        self._pool.shutdown(wait=False)
        if _CONTEXT is self:
            _CONTEXT = None
