"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

The reference's longest-sequence story is a full O(L²) attention on one
machine (TransformerLayer.scala:137; SURVEY.md §5 "Long-context: absent").
This module provides the capability the reference never had: the sequence
dimension is sharded across chips, and K/V blocks rotate around the ring via
``jax.lax.ppermute`` over ICI while each chip accumulates its queries' output
with the numerically-stable streaming-softmax (flash-attention) update.  Peak
memory per chip is one block pair instead of O(L²), and compute/communication
overlap rides the ring (cf. Ring Attention, Liu et al.; blockwise parallel
transformers).

Round 4 (VERDICT r03 weak #8): the per-hop block attention is the **Pallas
flash kernel** on TPU (``attention_stats`` — streaming K/V through VMEM
instead of materializing the (Lc, Lc) score tile in HBM), partials combined
with the exact flash update; under the causal mask, fully-masked hops
(key block entirely in the future) skip their matmuls via ``lax.switch``
(causal load-balancing: late ranks stop burning MXU on dead blocks).
Differentiability comes from a custom VJP whose backward runs the REVERSE
ring: dK/dV accumulators rotate with their blocks and arrive home after a
full circle, with each hop's score tile rematerialized (flash-style
O(block) memory).
"""

from __future__ import annotations

import functools
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.common.engine import SEQ_AXIS, get_zoo_context
from analytics_zoo_tpu.ops.pallas.flash_attention import (
    _attention_stats_reference,
    _flash_bwd_pallas,
    _interpret_forced,
    _pallas_available,
    attention_stats,
)

_NEG = -1e30


def _use_pallas_inner(ql) -> bool:
    return (_pallas_available() and ql.shape[-1] % 64 == 0
            and ql.shape[2] >= 128)


def _hop_stats(ql, k_blk, v_blk, kv_idx, my, causal, scale, lc):
    """One ring hop's partial attention, choosing the inner kernel."""
    if _use_pallas_inner(ql):
        if not causal:
            return attention_stats(ql, k_blk, v_blk, causal=False,
                                   scale=scale)

        def full(_):
            return attention_stats(ql, k_blk, v_blk, causal=False,
                                   scale=scale)

        def diag(_):
            return attention_stats(ql, k_blk, v_blk, causal=True,
                                   scale=scale)

        def skip(_):
            # key block entirely in the future: no MXU work at all
            return _skip_stats(ql)

        branch = jnp.where(kv_idx < my, 0, jnp.where(kv_idx == my, 1, 2))
        return lax.switch(branch, (full, diag, skip), None)
    # jnp inner: one general global-position mask covers all three cases
    # (shared streaming-stats semantics live in _attention_stats_reference)
    mask = None
    if causal:
        q_pos = my * lc + jnp.arange(lc)
        k_pos = kv_idx * lc + jnp.arange(lc)
        mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
    return _attention_stats_reference(ql, k_blk, v_blk, False, scale,
                                      mask=mask)


def _ring_fwd_scan(ql, kl, vl, axis_name, n_shards, causal, scale,
                   zigzag=False):
    my = lax.axis_index(axis_name)
    b, h, lc, d = ql.shape
    m0 = jnp.full((b, h, lc), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, lc), jnp.float32)
    acc0 = jnp.zeros(ql.shape, jnp.float32)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    def step(carry, i):
        m, l, acc, k_blk, v_blk = carry
        kv_idx = (my - i) % n_shards
        if zigzag:
            o_b, m_b, l_b = _zz_hop_stats(ql, k_blk, v_blk, kv_idx, my,
                                          n_shards, causal, scale)
        else:
            o_b, m_b, l_b = _hop_stats(ql, k_blk, v_blk, kv_idx, my,
                                       causal, scale, lc)
        # exact flash combine of two partials over disjoint key sets
        new_m = jnp.maximum(m, m_b)
        a_old = jnp.exp(m - new_m)
        a_new = jnp.exp(m_b - new_m)
        l = l * a_old + l_b * a_new
        acc = acc * a_old[..., None] + (
            o_b.astype(jnp.float32) * l_b[..., None]) * a_new[..., None]
        # rotate the K/V blocks one hop around the ring (ICI neighbor)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (new_m, l, acc, k_blk, v_blk), None

    (m, l, acc, _, _), _ = lax.scan(
        step, (m0, l0, acc0, kl, vl), jnp.arange(n_shards))
    out = (acc / jnp.maximum(l, 1e-20)[..., None]).astype(ql.dtype)
    return out, m, l


def _local_positions(rank, n_shards, lc, zigzag):
    """Global sequence positions of a rank's local block.  Contiguous:
    one run of lc; zigzag: pieces ``rank`` and ``2n-1-rank`` of lc/2."""
    if not zigzag:
        return rank * lc + jnp.arange(lc)
    half = lc // 2
    return jnp.concatenate([rank * half + jnp.arange(half),
                            (2 * n_shards - 1 - rank) * half
                            + jnp.arange(half)])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_core(ql, kl, vl, axis_name, n_shards, causal, scale, zigzag):
    out, _, _ = _ring_fwd_scan(ql, kl, vl, axis_name, n_shards, causal,
                               scale, zigzag)
    return out


def _ring_vjp_fwd(ql, kl, vl, axis_name, n_shards, causal, scale,
                  zigzag):
    out, m, l = _ring_fwd_scan(ql, kl, vl, axis_name, n_shards, causal,
                               scale, zigzag)
    return out, (ql, kl, vl, out, m, l)


_BWD_CHUNK = 256


def _flash_hop_bwd(ql, k_blk, v_blk, g, out, m, l, causal, scale):
    """One hop's (dq, dk, dv) through the Pallas backward kernels, using
    the ring's saved GLOBAL softmax stats (the kernels take m/l as inputs
    precisely so partial-attention backwards compose this way)."""
    dq, dk, dv, _ = _flash_bwd_pallas(
        ql, k_blk, v_blk, g, out, m, l, causal, scale,
        interpret=_interpret_forced())
    return (dq.astype(jnp.float32), dk.astype(jnp.float32),
            dv.astype(jnp.float32))


def _zero_hop_grads(ql, k_blk, v_blk):
    return (jnp.zeros(ql.shape, jnp.float32),
            jnp.zeros(k_blk.shape, jnp.float32),
            jnp.zeros(v_blk.shape, jnp.float32))


def _hop_grads_flash(ql, k_blk, v_blk, g, out, m, l, kv_idx, my, causal,
                     scale):
    """Contiguous-layout hop gradients via the Pallas kernels: full
    attend for past key blocks, causal diagonal for the own block, all
    zeros (no MXU work) for future blocks — mirroring `_hop_stats`."""
    if not causal:
        return _flash_hop_bwd(ql, k_blk, v_blk, g, out, m, l, False,
                              scale)

    def full(_):
        return _flash_hop_bwd(ql, k_blk, v_blk, g, out, m, l, False,
                              scale)

    def diag(_):
        return _flash_hop_bwd(ql, k_blk, v_blk, g, out, m, l, True,
                              scale)

    def skip(_):
        return _zero_hop_grads(ql, k_blk, v_blk)

    branch = jnp.where(kv_idx < my, 0, jnp.where(kv_idx == my, 1, 2))
    return lax.switch(branch, (full, diag, skip), None)


def _zz_quadrant_bwd(qp, kp, vp, gp, op, mp, lp, q_id, k_id, scale):
    """Backward of one zigzag (query piece, key piece) quadrant whose
    order is only known at run time — mirrors `_zz_quadrant`."""
    def full(_):
        return _flash_hop_bwd(qp, kp, vp, gp, op, mp, lp, False, scale)

    def diag(_):
        return _flash_hop_bwd(qp, kp, vp, gp, op, mp, lp, True, scale)

    def skip(_):
        return _zero_hop_grads(qp, kp, vp)

    branch = jnp.where(k_id < q_id, 0, jnp.where(k_id == q_id, 1, 2))
    return lax.switch(branch, (full, diag, skip), None)


def _zz_hop_grads_flash(ql, k_blk, v_blk, g, out, m, l, kv_owner, my, n,
                        scale):
    """Zigzag hop gradients via the Pallas kernels, quadrant by quadrant
    (mirrors `_zz_hop_stats`'s static/run-time case split): the low-id
    query piece never attends the high-id key piece (static skip); the
    high-id query piece always fully attends the low-id key piece; the
    low-low and high-high pairs branch at run time."""
    half = ql.shape[2] // 2
    q_lo, q_hi = _zz_piece_ids(my, n)
    k_lo, k_hi = _zz_piece_ids(kv_owner, n)
    qa, qb = ql[:, :, :half], ql[:, :, half:]
    ka, kb = k_blk[:, :, :half], k_blk[:, :, half:]
    va, vb = v_blk[:, :, :half], v_blk[:, :, half:]
    ga, gb = g[:, :, :half], g[:, :, half:]
    oa, ob = out[:, :, :half], out[:, :, half:]
    ma, mb = m[:, :, :half], m[:, :, half:]
    la, lb = l[:, :, :half], l[:, :, half:]

    dqa, dka_1, dva_1 = _zz_quadrant_bwd(qa, ka, va, ga, oa, ma, la,
                                         q_lo, k_lo, scale)
    dqb_1, dka_2, dva_2 = _flash_hop_bwd(qb, ka, va, gb, ob, mb, lb,
                                         False, scale)
    dqb_2, dkb, dvb = _zz_quadrant_bwd(qb, kb, vb, gb, ob, mb, lb,
                                       q_hi, k_hi, scale)
    dq = jnp.concatenate([dqa, dqb_1 + dqb_2], axis=2)
    dk = jnp.concatenate([dka_1 + dka_2, dkb], axis=2)
    dv = jnp.concatenate([dva_1 + dva_2, dvb], axis=2)
    return dq, dk, dv


def _ring_vjp_bwd(axis_name, n_shards, causal, scale, zigzag, res, g):
    """Reverse ring: rematerialize each hop's score tile from (q, k_blk)
    and the saved GLOBAL softmax stats (m, l); dK/dV accumulators ride the
    ring WITH their blocks, so after the full circle each shard holds
    exactly its own blocks' gradients — no gather, one ppermute per hop.
    Within a hop the key block is processed in chunks of ``_BWD_CHUNK`` via
    an inner scan, so live memory is O(lc·chunk), not O(lc²) — the flash
    rematerialization strategy.  Under the causal mask, hops whose key
    block is entirely in the future skip all five einsums (ds and p are
    identically zero there) — the same load-balancing as the forward."""
    ql, kl, vl, out, m, l = res
    my = lax.axis_index(axis_name)
    b, h, lc, d = ql.shape
    qf = ql.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    l_safe = jnp.maximum(l, 1e-20)
    # flash-bwd identity: D_i = dO_i . O_i
    big_d = jnp.sum(gf * out.astype(jnp.float32), axis=-1)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
    q_pos = _local_positions(my, n_shards, lc, zigzag)
    # the last chunk is zero-PADDED (not widened): the O(lc*chunk) memory
    # bound must hold for every lc, incl. lengths with no divisor <= 256
    ck = min(_BWD_CHUNK, lc)
    n_ck = -(-lc // ck)
    pad = n_ck * ck - lc

    def hop_grads(kv_idx, k_blk, v_blk):
        kf = jnp.pad(k_blk.astype(jnp.float32),
                     ((0, 0), (0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(v_blk.astype(jnp.float32),
                     ((0, 0), (0, 0), (0, pad), (0, 0)))
        kp_full = jnp.pad(_local_positions(kv_idx, n_shards, lc, zigzag),
                          (0, pad))

        def chunk(dq, ci):
            ks = ci * ck
            kc = lax.dynamic_slice_in_dim(kf, ks, ck, axis=2)
            vc = lax.dynamic_slice_in_dim(vf, ks, ck, axis=2)
            s = jnp.einsum("bhqd,bhkd->bhqk", qf, kc) * scale
            local_pos = ks + jnp.arange(ck)
            live = (local_pos < lc)[None, :]  # mask the zero padding
            if causal:
                k_pos = lax.dynamic_slice_in_dim(kp_full, ks, ck, axis=0)
                live = live & (q_pos[:, None] >= k_pos[None, :])
            s = jnp.where(live, s, _NEG)
            p = jnp.where(live, jnp.exp(s - m[..., None]), 0.0)
            p = p / l_safe[..., None]
            dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vc)
            ds = p * (dp - big_d[..., None])
            dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kc) * scale
            dkc = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
            dvc = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
            return dq, (dkc, dvc)

        dq_h, (dk_s, dv_s) = lax.scan(
            chunk, jnp.zeros(ql.shape, jnp.float32), jnp.arange(n_ck))
        dk_h = jnp.moveaxis(dk_s, 0, 2).reshape(
            b, h, n_ck * ck, d)[:, :, :lc]
        dv_h = jnp.moveaxis(dv_s, 0, 2).reshape(
            b, h, n_ck * ck, d)[:, :, :lc]
        return dq_h, dk_h, dv_h

    # Pallas hop backward when the inner kernel served the forward: the
    # kernels take the GLOBAL (m, l) as inputs, so each hop's partial
    # backward composes exactly; score tiles stay in VMEM instead of the
    # jnp chunk scan's HBM round-trips (the jnp path remains the
    # fallback and oracle).  Zigzag pieces are half-length, so gate on
    # the piece size.
    piece = lc // 2 if zigzag else lc
    use_flash_bwd = (_pallas_available() and d % 64 == 0 and piece >= 128)

    def step(carry, i):
        dq, k_blk, v_blk, dk_rot, dv_rot = carry
        kv_idx = (my - i) % n_shards

        if use_flash_bwd and zigzag:
            dq_h, dk_h, dv_h = _zz_hop_grads_flash(
                ql, k_blk, v_blk, g, out, m, l, kv_idx, my, n_shards,
                scale)
        elif use_flash_bwd:
            dq_h, dk_h, dv_h = _hop_grads_flash(
                ql, k_blk, v_blk, g, out, m, l, kv_idx, my, causal,
                scale)
        elif causal and not zigzag:
            def work(_):
                return hop_grads(kv_idx, k_blk, v_blk)

            def dead(_):
                z = jnp.zeros(ql.shape, jnp.float32)
                return z, jnp.zeros(kl.shape, jnp.float32), \
                    jnp.zeros(vl.shape, jnp.float32)

            # key block entirely in the future: no einsums at all
            dq_h, dk_h, dv_h = lax.cond(kv_idx <= my, work, dead, None)
        else:
            # zigzag: every hop carries useful work (that is the point)
            dq_h, dk_h, dv_h = hop_grads(kv_idx, k_blk, v_blk)
        dq = dq + dq_h
        dk_rot = dk_rot + dk_h
        dv_rot = dv_rot + dv_h
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        dk_rot = lax.ppermute(dk_rot, axis_name, perm)
        dv_rot = lax.ppermute(dv_rot, axis_name, perm)
        return (dq, k_blk, v_blk, dk_rot, dv_rot), None

    dq0 = jnp.zeros(ql.shape, jnp.float32)
    (dq, _, _, dk, dv), _ = lax.scan(
        step,
        (dq0, kl, vl, jnp.zeros(kl.shape, jnp.float32),
         jnp.zeros(vl.shape, jnp.float32)),
        jnp.arange(n_shards))
    return (dq.astype(ql.dtype), dk.astype(kl.dtype), dv.astype(vl.dtype))


_ring_core.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def _ring_attention_local(ql, kl, vl, *, axis_name: str, n_shards: int,
                          causal: bool, scale: float):
    """Per-shard body: ql/kl/vl are (B, H, Lc, D) local blocks."""
    return _ring_core(ql, kl, vl, axis_name, n_shards, causal, scale,
                      False)


def ring_attention(q, k, v, *, causal: bool = False, mesh=None,
                   axis_name: str = SEQ_AXIS, scale: float | None = None):
    """Sequence-parallel attention over a mesh ``seq`` axis.

    Args:
      q, k, v: (B, H, L, D) arrays (global view); L must divide evenly over
        the seq axis.  Under jit with a sharded mesh, pass arrays whose L dim
        is sharded with PartitionSpec(..., axis_name, ...).
      causal: lower-triangular masking over the *global* L positions.
    Returns: (B, H, L, D), L sharded like q.
    """
    mesh = mesh or get_zoo_context().mesh
    n = mesh.shape[axis_name]
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    if n == 1:
        from analytics_zoo_tpu.ops.attention import dot_product_attention

        return dot_product_attention(q, k, v, causal=causal, scale=scale)
    spec = P(None, None, axis_name, None)
    fn = jax.shard_map(
        partial(_ring_attention_local, axis_name=axis_name, n_shards=n,
                causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Zigzag ring attention (VERDICT r03 weak #8, causal load balancing):
# under a causal mask, the contiguous layout gives rank r exactly r+1
# useful hops — the last rank does n times the work of the first and sets
# the critical path.  The zigzag layout (each rank holds sequence pieces
# r AND 2n-1-r, the striped/zigzag-ring construction from the public
# long-context literature) makes every rank's useful work equal: piece r
# attends to r+1 pieces, piece 2n-1-r to 2n-r, summing to 2n+1 everywhere.
# ---------------------------------------------------------------------------


def _zz_piece_ids(rank, n):
    """(low_id, high_id) global piece ids held by ``rank``."""
    return rank, 2 * n - 1 - rank


def _zz_to(local, axis_name, n):
    """Contiguous local block (pieces 2r, 2r+1) -> zigzag (r, 2n-1-r).

    Two ppermutes (each a rank bijection) + a parity-based slot fix:
    rank r's zigzag low piece has id r (even iff r even), so even ranks
    take their low piece from the even-id route and odd ranks from the
    odd-id route.
    """
    half = local.shape[2] // 2
    h0, h1 = local[:, :, :half], local[:, :, half:]
    # piece 2r (even ids) routing; piece 2r+1 (odd ids) routing
    perm0 = [(r, 2 * r if 2 * r < n else 2 * n - 1 - 2 * r)
             for r in range(n)]
    perm1 = [(r, 2 * r + 1 if 2 * r + 1 < n else 2 * n - 2 - 2 * r)
             for r in range(n)]
    recv0 = lax.ppermute(h0, axis_name, perm0)
    recv1 = lax.ppermute(h1, axis_name, perm1)
    even = (lax.axis_index(axis_name) % 2) == 0
    low = jnp.where(even, recv0, recv1)
    high = jnp.where(even, recv1, recv0)
    return jnp.concatenate([low, high], axis=2)


def _zz_from(local, axis_name, n):
    """Zigzag local block (pieces r, 2n-1-r) -> contiguous (2r, 2r+1)."""
    half = local.shape[2] // 2
    low, high = local[:, :, :half], local[:, :, half:]
    even = (lax.axis_index(axis_name) % 2) == 0
    # the even-id piece on rank s is its low slot iff s is even
    send_even = jnp.where(even, low, high)
    send_odd = jnp.where(even, high, low)
    perm_even = [(s, (s if s % 2 == 0 else 2 * n - 1 - s) // 2)
                 for s in range(n)]
    perm_odd = [(s, ((2 * n - 1 - s if s % 2 == 0 else s) - 1) // 2)
                for s in range(n)]
    recv_even = lax.ppermute(send_even, axis_name, perm_even)
    recv_odd = lax.ppermute(send_odd, axis_name, perm_odd)
    return jnp.concatenate([recv_even, recv_odd], axis=2)


def _skip_stats(qp):
    """Zero partial stats (key block entirely in the future)."""
    b, h, q_len, _ = qp.shape
    return (jnp.zeros_like(qp),
            jnp.full((b, h, q_len), _NEG, jnp.float32),
            jnp.zeros((b, h, q_len), jnp.float32))


def _zz_quadrant(qp, k, v, q_id, k_id, scale):
    """Partial stats for one (query piece, key piece) pair whose order is
    only known at run time: full attend if the key piece is entirely in
    the past, causal-diagonal if it IS this piece, skip if in the
    future.  (Pairs with STATICALLY known order — a low-id query piece
    vs a high-id key piece and vice versa — never come through here;
    _zz_hop_stats resolves them at trace time.)"""
    def full(_):
        return attention_stats(qp, k, v, causal=False, scale=scale)

    def diag(_):
        return attention_stats(qp, k, v, causal=True, scale=scale)

    def skip(_):
        return _skip_stats(qp)

    branch = jnp.where(k_id < q_id, 0, jnp.where(k_id == q_id, 1, 2))
    return lax.switch(branch, (full, diag, skip), None)


def _merge_stats(a, b):
    """Exact flash combine of two (o, m, l) partials over disjoint keys."""
    o_a, m_a, l_a = a
    o_b, m_b, l_b = b
    m = jnp.maximum(m_a, m_b)
    w_a = jnp.exp(m_a - m)
    w_b = jnp.exp(m_b - m)
    l = l_a * w_a + l_b * w_b
    o = (o_a.astype(jnp.float32) * (l_a * w_a)[..., None]
         + o_b.astype(jnp.float32) * (l_b * w_b)[..., None])
    # o is l-weighted (unnormalized); callers divide by l at the end
    return o, m, l


def _zz_hop_stats(ql, k_blk, v_blk, kv_owner, my, n, causal, scale):
    """One causal zigzag hop.  Piece ids: queries hold (my, 2n-1-my),
    keys hold (kv_owner, 2n-1-kv_owner).  Two of the four quadrants are
    static — a low-id query (< n) is ALWAYS in the past of a high-id key
    (>= n) [skip], and a high-id query is ALWAYS after a low-id key
    [full] — so only the low-low and high-high pairs need a run-time
    branch.  Per hop: <= 3 flash-stat tiles, equal on every rank."""
    half = ql.shape[2] // 2
    q_lo, q_hi = _zz_piece_ids(my, n)
    k_lo, k_hi = _zz_piece_ids(kv_owner, n)
    qa, qb = ql[:, :, :half], ql[:, :, half:]
    ka, kb = k_blk[:, :, :half], k_blk[:, :, half:]
    va, vb = v_blk[:, :, :half], v_blk[:, :, half:]

    # low query: the high key piece is always in the future — one branch
    o_a, m_a, l_a = _zz_quadrant(qa, ka, va, q_lo, k_lo, scale)
    # high query: the low key piece is always in the past (full), the
    # high key piece order is run-time
    s_full = attention_stats(qb, ka, va, causal=False, scale=scale)
    s_hh = _zz_quadrant(qb, kb, vb, q_hi, k_hi, scale)
    o_b, m_b, l_b = _merge_stats(s_full, s_hh)
    o_b = o_b / jnp.maximum(l_b, 1e-20)[..., None]  # back to normalized

    o = jnp.concatenate([o_a.astype(jnp.float32), o_b], axis=2)
    m = jnp.concatenate([m_a, m_b], axis=2)
    l = jnp.concatenate([l_a, l_b], axis=2)
    return o.astype(ql.dtype), m, l


def _zz_ring_local(ql, kl, vl, axis_name, n_shards, causal, scale):
    """Per-shard zigzag body on CONTIGUOUS locals: relayout, then the
    SAME custom-VJP ring core as the contiguous path (zigzag=True swaps
    the per-hop stats and position math), relayout back.  The backward is
    therefore the memory-bounded reverse ring (O(lc*chunk) live, Pallas
    fwd never autodiffed), not autodiff through the scan."""
    ql_z = _zz_to(ql, axis_name, n_shards)
    kl_z = _zz_to(kl, axis_name, n_shards)
    vl_z = _zz_to(vl, axis_name, n_shards)
    out_z = _ring_core(ql_z, kl_z, vl_z, axis_name, n_shards, causal,
                       scale, True)
    return _zz_from(out_z, axis_name, n_shards)


def zigzag_ring_attention(q, k, v, *, causal: bool = True, mesh=None,
                          axis_name: str = SEQ_AXIS,
                          scale: float | None = None):
    """Causal-load-balanced sequence-parallel attention.

    Same contract as :func:`ring_attention` (contiguous L sharding in and
    out — the zigzag relayout is internal, two ppermutes each way), but
    every rank does equal useful work under the causal mask instead of
    rank r doing r+1 hops' worth.  Local sequence length must be even.
    """
    mesh = mesh or get_zoo_context().mesh
    n = mesh.shape[axis_name]
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    if n == 1 or not causal:
        # without the causal mask there is no load imbalance to fix —
        # the contiguous ring gives the identical result without the
        # four relayout ppermutes
        return ring_attention(q, k, v, causal=causal, mesh=mesh,
                              axis_name=axis_name, scale=scale)
    if q.shape[2] % n != 0 or (q.shape[2] // n) % 2 != 0:
        raise ValueError(
            f"zigzag needs an even local sequence length; global "
            f"L={q.shape[2]} over {n} shards gives "
            f"{q.shape[2] / n:g}")
    spec = P(None, None, axis_name, None)
    fn = jax.shard_map(
        partial(_zz_ring_local, axis_name=axis_name, n_shards=n,
                causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
