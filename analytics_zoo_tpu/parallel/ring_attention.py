"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

The reference's longest-sequence story is a full O(L²) attention on one
machine (TransformerLayer.scala:137; SURVEY.md §5 "Long-context: absent").
This module provides the capability the reference never had: the sequence
dimension is sharded across chips, and K/V blocks rotate around the ring via
``jax.lax.ppermute`` over ICI while each chip accumulates its queries' output
with the numerically-stable streaming-softmax (flash-attention) update.  Peak
memory per chip is O(L·L/n) scores for one block pair instead of O(L²), and
compute/communication overlap rides the ring (cf. Ring Attention,
Liu et al.; blockwise parallel transformers).

Differentiable end-to-end: the ring is a ``lax.scan`` of ppermutes, so
jax.grad produces the reverse ring automatically.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.common.engine import SEQ_AXIS, get_zoo_context

_NEG = -1e30


def _ring_attention_local(ql, kl, vl, *, axis_name: str, n_shards: int,
                          causal: bool, scale: float):
    """Per-shard body: ql/kl/vl are (B, H, Lc, D) local blocks."""
    my = lax.axis_index(axis_name)
    b, h, lc, d = ql.shape
    q_pos = my * lc + jnp.arange(lc)

    m0 = jnp.full((b, h, lc), _NEG, ql.dtype)
    l0 = jnp.zeros((b, h, lc), ql.dtype)
    acc0 = jnp.zeros_like(ql)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    def step(carry, i):
        m, l, acc, k_blk, v_blk = carry
        kv_idx = (my - i) % n_shards
        k_pos = kv_idx * lc + jnp.arange(lc)
        scores = jnp.einsum("bhqd,bhkd->bhqk", ql, k_blk) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask, scores, _NEG)
        new_m = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[..., None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk
        )
        # rotate the K/V blocks one hop around the ring (ICI neighbor)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (new_m, l, acc, k_blk, v_blk), None

    (m, l, acc, _, _), _ = lax.scan(
        step, (m0, l0, acc0, kl, vl), jnp.arange(n_shards)
    )
    return acc / jnp.maximum(l, 1e-20)[..., None]


def ring_attention(q, k, v, *, causal: bool = False, mesh=None,
                   axis_name: str = SEQ_AXIS, scale: float | None = None):
    """Sequence-parallel attention over a mesh ``seq`` axis.

    Args:
      q, k, v: (B, H, L, D) arrays (global view); L must divide evenly over
        the seq axis.  Under jit with a sharded mesh, pass arrays whose L dim
        is sharded with PartitionSpec(..., axis_name, ...).
      causal: lower-triangular masking over the *global* L positions.
    Returns: (B, H, L, D), L sharded like q.
    """
    mesh = mesh or get_zoo_context().mesh
    n = mesh.shape[axis_name]
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    if n == 1:
        from analytics_zoo_tpu.ops.attention import dot_product_attention

        return dot_product_attention(q, k, v, causal=causal, scale=scale)
    spec = P(None, None, axis_name, None)
    fn = jax.shard_map(
        partial(_ring_attention_local, axis_name=axis_name, n_shards=n,
                causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
