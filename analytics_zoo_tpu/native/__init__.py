"""Native (C++) host-path acceleration.

The reference reaches native code for its data path and kernels over JNI
(SURVEY.md §2.3).  On TPU the device math belongs to XLA; the justified
native component is the *host* data path (SURVEY.md: "high-throughput
host-side decode/augment feeding infeed").  This package builds a small C++
library (ctypes-bound) providing:

- crc32c (TFRecord framing hot loop)
- uint8 image normalize/flip/crop batch kernels for the host feed

Build is lazy and optional: ``lib`` is None (pure-python fallbacks apply)
until :func:`build_native` succeeds; import never fails without a compiler.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

logger = logging.getLogger("analytics_zoo_tpu")

_HERE = os.path.dirname(__file__)
_SO = os.path.join(_HERE, "libzoonative.so")
_SRC = os.path.join(_HERE, "zoonative.cpp")


class _NativeLib:
    def __init__(self, cdll):
        self._dll = cdll
        self._dll.zoo_crc32c.restype = ctypes.c_uint32
        self._dll.zoo_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        self._dll.zoo_normalize_u8.restype = None
        self._dll.zoo_normalize_u8.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
            ctypes.c_size_t, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ]
        self._dll.zoo_assemble_batch.restype = None
        self._dll.zoo_assemble_batch.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32,
        ]
        self._dll.zoo_resize_bilinear_u8.restype = None
        self._dll.zoo_resize_bilinear_u8.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ]

    def crc32c(self, data: bytes) -> int:
        return self._dll.zoo_crc32c(data, len(data))

    def normalize_u8(self, img, mean, std):
        """uint8 HWC image batch -> float32 normalized, in C."""
        import numpy as np

        img = np.ascontiguousarray(img, dtype=np.uint8)
        ch = img.shape[-1]
        out = np.empty(img.shape, dtype=np.float32)
        mean = np.ascontiguousarray(mean, dtype=np.float32)
        std = np.ascontiguousarray(std, dtype=np.float32)
        self._dll.zoo_normalize_u8(
            img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            img.size, ch,
            mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return out

    def assemble_batch(self, images, offsets, flips, out_h, out_w,
                       n_threads=None):
        """Pack variable-size HWC uint8 images into one (N, oh, ow, C)
        uint8 batch with per-image crop offsets + horizontal flips, on C++
        threads.  ``offsets``/``flips`` come from the caller's seeded RNG
        so augmentation replay stays exact."""
        import numpy as np

        n = len(images)
        ch = images[0].shape[-1]
        imgs = [np.ascontiguousarray(im, dtype=np.uint8) for im in images]
        ptrs = (ctypes.c_void_p * n)(
            *[im.ctypes.data_as(ctypes.c_void_p).value for im in imgs])
        hw = np.ascontiguousarray(
            [[im.shape[0], im.shape[1]] for im in imgs], dtype=np.int32)
        off = np.ascontiguousarray(offsets, dtype=np.int32)
        flp = np.ascontiguousarray(flips, dtype=np.uint8)
        out = np.empty((n, out_h, out_w, ch), dtype=np.uint8)
        if n_threads is None:
            n_threads = min(8, os.cpu_count() or 1)
        self._dll.zoo_assemble_batch(
            ptrs,
            hw.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            off.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            flp.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            n, out_h, out_w, ch, int(n_threads),
        )
        return out

    def resize_bilinear(self, batch, out_h, out_w, n_threads=None):
        """(N, H, W, C) uint8 -> (N, oh, ow, C) uint8, half-pixel-center
        bilinear (cv2 INTER_LINEAR convention), on C++ threads."""
        import numpy as np

        batch = np.ascontiguousarray(batch, dtype=np.uint8)
        n, ih, iw, ch = batch.shape
        out = np.empty((n, out_h, out_w, ch), dtype=np.uint8)
        if n_threads is None:
            n_threads = min(8, os.cpu_count() or 1)
        self._dll.zoo_resize_bilinear_u8(
            batch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            n, ih, iw, out_h, out_w, ch, int(n_threads),
        )
        return out


def build_native(force: bool = False):
    """Compile the C++ library with g++ (no external deps)."""
    global lib
    if os.path.exists(_SO) and not force:
        pass
    else:
        if not _compile(_SO):
            return None
    try:
        lib = _NativeLib(ctypes.CDLL(_SO))
        return lib
    except AttributeError:
        # a stale .so from an older source (missing a new symbol).  glibc
        # dlopen caches by path string IN-PROCESS, so rebuilding at the
        # same path cannot replace the already-loaded stale mapping:
        # compile to a UNIQUE path for this process's load, and install a
        # canonical copy at _SO for future imports.
        if force:
            logger.warning("native lib missing symbols even after rebuild")
            return None
        import tempfile

        uniq = os.path.join(tempfile.mkdtemp(prefix="zoonative-"),
                            "libzoonative.so")
        if not _compile(uniq):
            return None
        try:
            lib = _NativeLib(ctypes.CDLL(uniq))
        except (OSError, AttributeError) as e:
            logger.warning("native reload failed: %s", e)
            return None
        try:  # refresh the canonical .so so the NEXT process loads fresh
            import shutil

            shutil.copy(uniq, _SO + ".new")
            os.replace(_SO + ".new", _SO)
        except OSError:
            pass
        return lib
    except OSError as e:
        logger.warning("native load failed: %s", e)
        return None


def _compile(out_path: str) -> bool:
    # compile to a temp then rename: atomic for concurrent builders
    tmp = out_path + ".build"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-march=native",
           "-pthread", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, out_path)
        return True
    except Exception as e:  # no compiler / failed build → fallback
        logger.warning("native build failed: %s", e)
        return False


lib = None
if os.path.exists(_SO):
    try:
        lib = _NativeLib(ctypes.CDLL(_SO))
    except (OSError, AttributeError):
        # unreadable or STALE .so (older source without a new symbol) —
        # keep the import-never-fails guarantee; build_native() rebuilds
        lib = None
