"""Native (C++) host-path acceleration.

The reference reaches native code for its data path and kernels over JNI
(SURVEY.md §2.3).  On TPU the device math belongs to XLA; the justified
native component is the *host* data path (SURVEY.md: "high-throughput
host-side decode/augment feeding infeed").  This package builds a small C++
library (ctypes-bound) providing:

- crc32c (TFRecord framing hot loop)
- uint8 image normalize/flip/crop batch kernels for the host feed

Build is lazy and optional: ``lib`` is None (pure-python fallbacks apply)
until :func:`build_native` succeeds; import never fails without a compiler.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

logger = logging.getLogger("analytics_zoo_tpu")

_HERE = os.path.dirname(__file__)
_SO = os.path.join(_HERE, "libzoonative.so")
_SRC = os.path.join(_HERE, "zoonative.cpp")


class _NativeLib:
    def __init__(self, cdll):
        self._dll = cdll
        self._dll.zoo_crc32c.restype = ctypes.c_uint32
        self._dll.zoo_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        self._dll.zoo_normalize_u8.restype = None
        self._dll.zoo_normalize_u8.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
            ctypes.c_size_t, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ]

    def crc32c(self, data: bytes) -> int:
        return self._dll.zoo_crc32c(data, len(data))

    def normalize_u8(self, img, mean, std):
        """uint8 HWC image batch -> float32 normalized, in C."""
        import numpy as np

        img = np.ascontiguousarray(img, dtype=np.uint8)
        ch = img.shape[-1]
        out = np.empty(img.shape, dtype=np.float32)
        mean = np.ascontiguousarray(mean, dtype=np.float32)
        std = np.ascontiguousarray(std, dtype=np.float32)
        self._dll.zoo_normalize_u8(
            img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            img.size, ch,
            mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return out


def build_native(force: bool = False):
    """Compile the C++ library with g++ (no external deps)."""
    global lib
    if os.path.exists(_SO) and not force:
        pass
    else:
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-march=native",
               "-o", _SO, _SRC]
        try:
            subprocess.run(cmd, check=True, capture_output=True)
        except Exception as e:  # no compiler / failed build → fallback
            logger.warning("native build failed: %s", e)
            return None
    try:
        lib = _NativeLib(ctypes.CDLL(_SO))
        return lib
    except OSError as e:
        logger.warning("native load failed: %s", e)
        return None


lib = None
if os.path.exists(_SO):
    try:
        lib = _NativeLib(ctypes.CDLL(_SO))
    except OSError:
        lib = None
