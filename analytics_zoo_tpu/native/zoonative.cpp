// Native host-path kernels for analytics_zoo_tpu.
//
// The reference reaches MKL/OpenCV through JNI for its host data path
// (SURVEY.md §2.3); the TPU rebuild keeps device math in XLA and uses this
// small library for the host-side hot loops: CRC32C for TFRecord framing
// and uint8 image normalization feeding the per-chip infeed.
//
// Build: g++ -O3 -shared -fPIC -march=native -o libzoonative.so zoonative.cpp

#include <cstddef>
#include <cstdint>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli), slice-by-8 table driven
// ---------------------------------------------------------------------------

static uint32_t kCrcTable[8][256];
static bool kCrcInit = false;

static void crc_init() {
  const uint32_t poly = 0x82F63B78u;
  for (int i = 0; i < 256; ++i) {
    uint32_t c = (uint32_t)i;
    for (int k = 0; k < 8; ++k) c = (c >> 1) ^ ((c & 1) ? poly : 0);
    kCrcTable[0][i] = c;
  }
  for (int t = 1; t < 8; ++t) {
    for (int i = 0; i < 256; ++i) {
      uint32_t c = kCrcTable[t - 1][i];
      kCrcTable[t][i] = (c >> 8) ^ kCrcTable[0][c & 0xFF];
    }
  }
  kCrcInit = true;
}

uint32_t zoo_crc32c(const char* data, size_t n) {
  if (!kCrcInit) crc_init();
  uint32_t crc = 0xFFFFFFFFu;
  const uint8_t* p = (const uint8_t*)data;
  while (n >= 8) {
    crc ^= (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
    uint32_t hi = (uint32_t)p[4] | ((uint32_t)p[5] << 8) |
                  ((uint32_t)p[6] << 16) | ((uint32_t)p[7] << 24);
    crc = kCrcTable[7][crc & 0xFF] ^ kCrcTable[6][(crc >> 8) & 0xFF] ^
          kCrcTable[5][(crc >> 16) & 0xFF] ^ kCrcTable[4][crc >> 24] ^
          kCrcTable[3][hi & 0xFF] ^ kCrcTable[2][(hi >> 8) & 0xFF] ^
          kCrcTable[1][(hi >> 16) & 0xFF] ^ kCrcTable[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ kCrcTable[0][(crc ^ *p++) & 0xFF];
  return crc ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// uint8 HWC image batch -> float32 (x - mean[c]) / std[c]
// ---------------------------------------------------------------------------

void zoo_normalize_u8(const uint8_t* in, float* out, size_t n,
                      size_t channels, const float* mean, const float* std) {
  float inv[16];
  size_t c = channels < 16 ? channels : 16;
  for (size_t i = 0; i < c; ++i) inv[i] = 1.0f / std[i];
  for (size_t i = 0; i < n; ++i) {
    size_t ch = i % channels;
    out[i] = ((float)in[i] - mean[ch]) * inv[ch];
  }
}

}  // extern "C"
