// Native host-path kernels for analytics_zoo_tpu.
//
// The reference reaches MKL/OpenCV through JNI for its host data path
// (SURVEY.md §2.3); the TPU rebuild keeps device math in XLA and uses this
// small library for the host-side hot loops: CRC32C for TFRecord framing
// and uint8 image normalization feeding the per-chip infeed.
//
// Build: g++ -O3 -shared -fPIC -march=native -o libzoonative.so zoonative.cpp

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli), slice-by-8 table driven
// ---------------------------------------------------------------------------

static uint32_t kCrcTable[8][256];
static bool kCrcInit = false;

static void crc_init() {
  const uint32_t poly = 0x82F63B78u;
  for (int i = 0; i < 256; ++i) {
    uint32_t c = (uint32_t)i;
    for (int k = 0; k < 8; ++k) c = (c >> 1) ^ ((c & 1) ? poly : 0);
    kCrcTable[0][i] = c;
  }
  for (int t = 1; t < 8; ++t) {
    for (int i = 0; i < 256; ++i) {
      uint32_t c = kCrcTable[t - 1][i];
      kCrcTable[t][i] = (c >> 8) ^ kCrcTable[0][c & 0xFF];
    }
  }
  kCrcInit = true;
}

uint32_t zoo_crc32c(const char* data, size_t n) {
  if (!kCrcInit) crc_init();
  uint32_t crc = 0xFFFFFFFFu;
  const uint8_t* p = (const uint8_t*)data;
  while (n >= 8) {
    crc ^= (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
    uint32_t hi = (uint32_t)p[4] | ((uint32_t)p[5] << 8) |
                  ((uint32_t)p[6] << 16) | ((uint32_t)p[7] << 24);
    crc = kCrcTable[7][crc & 0xFF] ^ kCrcTable[6][(crc >> 8) & 0xFF] ^
          kCrcTable[5][(crc >> 16) & 0xFF] ^ kCrcTable[4][crc >> 24] ^
          kCrcTable[3][hi & 0xFF] ^ kCrcTable[2][(hi >> 8) & 0xFF] ^
          kCrcTable[1][(hi >> 16) & 0xFF] ^ kCrcTable[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ kCrcTable[0][(crc ^ *p++) & 0xFF];
  return crc ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// uint8 HWC image batch -> float32 (x - mean[c]) / std[c]
// ---------------------------------------------------------------------------

void zoo_normalize_u8(const uint8_t* in, float* out, size_t n,
                      size_t channels, const float* mean, const float* std) {
  float inv[16];
  size_t c = channels < 16 ? channels : 16;
  for (size_t i = 0; i < c; ++i) inv[i] = 1.0f / std[i];
  for (size_t i = 0; i < n; ++i) {
    size_t ch = i % channels;
    out[i] = ((float)in[i] - mean[ch]) * inv[ch];
  }
}

// ---------------------------------------------------------------------------
// Threaded batch assembly: N variable-size HWC uint8 images -> one
// contiguous (N, oh, ow, ch) uint8 batch with per-image crop offsets and
// horizontal flips.  This is the host-side hot loop that keeps the
// per-chip infeed fed (SURVEY.md §2.3: "high-throughput host-side
// decode/augment feeding infeed" — the one justified native component).
// Crop offsets / flip flags come from the CALLER (seeded Python RNG), so
// augmentation replay after checkpoint-resume stays exact.
// ---------------------------------------------------------------------------

void zoo_assemble_batch(const uint8_t* const* imgs,
                        const int32_t* hw,    // (N, 2): src h, w
                        const int32_t* off,   // (N, 2): crop y0, x0
                        const uint8_t* flip,  // (N,): 1 = mirror
                        uint8_t* out, int32_t n, int32_t oh, int32_t ow,
                        int32_t ch, int32_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n) n_threads = n;
  auto work = [&](int32_t start, int32_t end) {
    for (int32_t i = start; i < end; ++i) {
      const uint8_t* src = imgs[i];
      const int32_t w = hw[2 * i + 1];
      const int32_t y0 = off[2 * i], x0 = off[2 * i + 1];
      uint8_t* dst_img = out + (size_t)i * oh * ow * ch;
      for (int32_t y = 0; y < oh; ++y) {
        const uint8_t* srow = src + ((size_t)(y0 + y) * w + x0) * ch;
        uint8_t* drow = dst_img + (size_t)y * ow * ch;
        if (!flip[i]) {
          memcpy(drow, srow, (size_t)ow * ch);
        } else {
          for (int32_t x = 0; x < ow; ++x)
            memcpy(drow + (size_t)x * ch,
                   srow + (size_t)(ow - 1 - x) * ch, (size_t)ch);
        }
      }
    }
  };
  if (n_threads == 1) {
    work(0, n);
    return;
  }
  std::vector<std::thread> pool;
  int32_t per = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int32_t s = t * per, e = s + per < n ? s + per : n;
    if (s >= e) break;
    pool.emplace_back(work, s, e);
  }
  for (auto& th : pool) th.join();
}

// ---------------------------------------------------------------------------
// Threaded bilinear resize: N same-size HWC uint8 images -> (N, oh, ow, ch)
// uint8.  Half-pixel-center sampling with edge clamping — the cv2
// INTER_LINEAR convention, so the Python oracle (ImageResize/cv2) and the
// native path agree to rounding.  Completes the native host preprocess
// chain: resize (here) -> crop/flip (zoo_assemble_batch) -> normalize
// (zoo_normalize_u8).
// ---------------------------------------------------------------------------

void zoo_resize_bilinear_u8(const uint8_t* in, uint8_t* out, int32_t n,
                            int32_t ih, int32_t iw, int32_t oh, int32_t ow,
                            int32_t ch, int32_t n_threads) {
  const float sy = (float)ih / (float)oh;
  const float sx = (float)iw / (float)ow;
  // Per-output-column sampling data is identical across rows and images:
  // precompute once.
  std::vector<int32_t> x0s(ow), x1s(ow);
  std::vector<float> fxs(ow);
  for (int32_t x = 0; x < ow; ++x) {
    float src = ((float)x + 0.5f) * sx - 0.5f;
    if (src < 0) src = 0;
    int32_t x0 = (int32_t)src;
    if (x0 > iw - 1) x0 = iw - 1;
    int32_t x1 = x0 + 1 < iw ? x0 + 1 : iw - 1;
    x0s[x] = x0;
    x1s[x] = x1;
    fxs[x] = src - (float)x0;
  }
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n) n_threads = n;
  auto work = [&](int32_t start, int32_t end) {
    for (int32_t i = start; i < end; ++i) {
      const uint8_t* src_img = in + (size_t)i * ih * iw * ch;
      uint8_t* dst_img = out + (size_t)i * oh * ow * ch;
      for (int32_t y = 0; y < oh; ++y) {
        float srcy = ((float)y + 0.5f) * sy - 0.5f;
        if (srcy < 0) srcy = 0;
        int32_t y0 = (int32_t)srcy;
        if (y0 > ih - 1) y0 = ih - 1;
        int32_t y1 = y0 + 1 < ih ? y0 + 1 : ih - 1;
        float fy = srcy - (float)y0;
        const uint8_t* r0 = src_img + (size_t)y0 * iw * ch;
        const uint8_t* r1 = src_img + (size_t)y1 * iw * ch;
        uint8_t* drow = dst_img + (size_t)y * ow * ch;
        for (int32_t x = 0; x < ow; ++x) {
          const uint8_t* p00 = r0 + (size_t)x0s[x] * ch;
          const uint8_t* p01 = r0 + (size_t)x1s[x] * ch;
          const uint8_t* p10 = r1 + (size_t)x0s[x] * ch;
          const uint8_t* p11 = r1 + (size_t)x1s[x] * ch;
          float fx = fxs[x];
          for (int32_t c = 0; c < ch; ++c) {
            float top = (float)p00[c] + fx * ((float)p01[c] - (float)p00[c]);
            float bot = (float)p10[c] + fx * ((float)p11[c] - (float)p10[c]);
            float v = top + fy * (bot - top);
            drow[(size_t)x * ch + c] = (uint8_t)(v + 0.5f);
          }
        }
      }
    }
  };
  if (n_threads == 1) {
    work(0, n);
    return;
  }
  std::vector<std::thread> pool;
  int32_t per = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int32_t s = t * per, e = s + per < n ? s + per : n;
    if (s >= e) break;
    pool.emplace_back(work, s, e);
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
