"""analytics_zoo_tpu — a TPU-native analytics + AI framework.

A ground-up JAX/XLA re-design of the capabilities of Analytics Zoo
(reference: /root/reference, a Scala/Spark/BigDL system). Where the
reference runs distributed deep learning as Spark jobs with a
block-manager all-reduce (reference docs/docs/wp-bigdl.md:148-164), this
framework compiles models to single SPMD XLA programs over a
``jax.sharding.Mesh`` and all-reduces gradients with ``jax.lax.psum``
over ICI.

Public surface (mirrors the reference's pyzoo package layout,
pyzoo/zoo/__init__.py):

- ``analytics_zoo_tpu.init_zoo_context`` — engine init (reference
  ``init_nncontext``, pyzoo/zoo/common/nncontext.py:104)
- ``analytics_zoo_tpu.pipeline.api.keras`` — Keras-1-style model API
- ``analytics_zoo_tpu.pipeline.api.autograd`` — Variable/CustomLoss
- ``analytics_zoo_tpu.feature`` — FeatureSet data layer
- ``analytics_zoo_tpu.models`` — built-in model zoo
- ``analytics_zoo_tpu.pipeline.estimator`` — Estimator training API
- ``analytics_zoo_tpu.pipeline.inference`` — pooled InferenceModel
"""

__version__ = "0.1.0"

# The runtime sanitizer must patch threading BEFORE any package module
# allocates a lock, so this hook runs first.  The env check happens
# HERE so the disabled path imports nothing — with ZOO_SAN unset, no
# analysis module loads and threading.Lock keeps its builtin identity
# (both pinned by tests).
import os as _os  # noqa: E402

if _os.environ.get("ZOO_SAN") == "1":
    from analytics_zoo_tpu.analysis.sanitizer import maybe_install \
        as _zoo_san_maybe_install

    _zoo_san_maybe_install()

from analytics_zoo_tpu.common.engine import (  # noqa: F401
    ZooConfig,
    ZooContext,
    get_zoo_context,
    init_zoo_context,
)
