"""Image classification zoo (reference
zoo/.../models/image/imageclassification): ImageClassifier with per-model
preprocessing configs and LabelOutput postprocess."""

from analytics_zoo_tpu.models.image.imageclassification.classifier import (
    ImageClassificationConfig,
    ImageClassifier,
    ImagenetConfig,
    LabelOutput,
)

__all__ = [
    "ImageClassifier",
    "ImageClassificationConfig",
    "ImagenetConfig",
    "LabelOutput",
]
