"""ImageClassifier — classification zoo model with config-driven
preprocessing.

Reference: imageclassification/ImageClassifier.scala:37 (``loadModel`` +
``predictImageSet`` with a per-model ``ImageConfigure``) and
ImageClassificationConfig.scala:31-188 (the registry mapping model names to
preprocess chains: resize 256 -> center crop 224 -> channel normalize with
imagenet mean/std) plus the ``LabelOutput`` postprocess attaching class
names + probabilities.
"""

from __future__ import annotations

import numpy as np

from analytics_zoo_tpu.feature.image.imageset import ImageSet
from analytics_zoo_tpu.feature.image.transforms import (
    ImageCenterCrop,
    ImageChannelNormalize,
    ImageResize,
)
from analytics_zoo_tpu.models.common import ZooModel

IMAGENET_MEAN = (123.68, 116.779, 103.939)
IMAGENET_STD = (1.0, 1.0, 1.0)


class ImageClassificationConfig:
    """Preprocess chain + metadata for one model family (reference
    ImageConfigure)."""

    def __init__(self, resize: int = 256, crop: int = 224,
                 mean=IMAGENET_MEAN, std=IMAGENET_STD, label_map=None):
        self.resize = resize
        self.crop = crop
        self.mean = tuple(mean)
        self.std = tuple(std)
        self.label_map = label_map

    def preprocessing(self):
        from analytics_zoo_tpu.feature.common import FnPreprocessing

        if len(self.mean) == 3:
            norm = ImageChannelNormalize(*self.mean, *self.std)
        else:  # grayscale / arbitrary channel count
            mean = np.asarray(self.mean, np.float32)
            std = np.asarray(self.std, np.float32)
            norm = FnPreprocessing(
                lambda img: (np.asarray(img, np.float32) - mean) / std)
        return (ImageResize(self.resize, self.resize)
                >> ImageCenterCrop(self.crop, self.crop)
                >> norm)


def ImagenetConfig(crop: int = 224) -> ImageClassificationConfig:
    """Reference ImagenetConfig (ImageClassificationConfig.scala:31-188)."""
    return ImageClassificationConfig(resize=256, crop=crop)


_CONFIGS = {
    "resnet-50": ImagenetConfig(224),
    "resnet-18": ImagenetConfig(224),
    # canonical input plans per family (reference ImageClassificationConfig
    # preprocess chains): alexnet 227, inception-v3 299
    "alexnet": ImageClassificationConfig(resize=256, crop=227),
    "inception-v3": ImageClassificationConfig(resize=320, crop=299),
    "lenet": ImageClassificationConfig(resize=28, crop=28, mean=(0,),
                                       std=(255.0,)),
}


def _config_for(model_name: str) -> ImageClassificationConfig:
    base = model_name.removesuffix("-quantize").removesuffix("-int8")
    return _CONFIGS.get(base, ImagenetConfig())


class LabelOutput:
    """Attach class names + sorted probabilities to raw predictions
    (reference LabelOutput.scala)."""

    def __init__(self, label_map=None, top_k: int = 5):
        self.label_map = label_map
        self.top_k = top_k

    def __call__(self, probs: np.ndarray):
        probs = np.asarray(probs)
        order = np.argsort(-probs, axis=-1)[..., :self.top_k]
        top_p = np.take_along_axis(probs, order, axis=-1)
        out = []
        for idx_row, p_row in zip(order, top_p):
            names = [
                self.label_map[int(i)] if self.label_map else int(i)
                for i in idx_row
            ]
            out.append(list(zip(names, p_row.tolist())))
        return out


class ImageClassifier(ZooModel):
    """Classification zoo model (reference ImageClassifier.scala:37).

    ``ImageClassifier(model_name)`` builds the named architecture with the
    matching preprocess config; ``ImageClassifier(model=net)`` wraps an
    existing KerasNet.
    """

    def __init__(self, model_name: str = "resnet-50", classes: int = 1000,
                 model=None, config: ImageClassificationConfig | None = None):
        self.model_name = model_name
        self.classes = classes
        self._provided = model
        self.config = config or _config_for(model_name)
        super().__init__()

    def build_model(self):
        if self._provided is not None:
            return self._provided
        # The reference's "<model>-quantize"/"-int8" variants
        # (ImageClassificationConfig.scala:31-50) are a deployment pass
        # here: build the same graph, then
        # InferenceModel.optimize("int8", ...) quantizes it.
        name = self.model_name
        for suffix in ("-quantize", "-int8"):
            if name.endswith(suffix):
                name = name[: -len(suffix)]
        shape = (self.config.crop, self.config.crop, 3)
        if name.startswith("resnet"):
            from analytics_zoo_tpu.models.resnet import ResNet

            depth = int(name.split("-")[1])
            return ResNet.image_net(depth, classes=self.classes,
                                    input_shape=shape)
        if name == "lenet":
            from analytics_zoo_tpu.models.lenet import build_lenet

            return build_lenet(classes=self.classes)
        if name == "inception-v1":
            from analytics_zoo_tpu.models.inception import Inception

            return Inception.v1(classes=self.classes, input_shape=shape)
        if name == "inception-v3":
            from analytics_zoo_tpu.models.inception import inception_v3

            return inception_v3(classes=self.classes, input_shape=shape)
        from analytics_zoo_tpu.models import imagenet_zoo as zoo_nets

        factories = {
            "alexnet": zoo_nets.alexnet,
            "vgg-16": lambda **kw: zoo_nets.vgg(16, **kw),
            "vgg-19": lambda **kw: zoo_nets.vgg(19, **kw),
            "densenet-121": lambda **kw: zoo_nets.densenet(121, **kw),
            "densenet-161": lambda **kw: zoo_nets.densenet(161, **kw),
            "squeezenet": zoo_nets.squeezenet,
            "mobilenet": zoo_nets.mobilenet,
            "mobilenet-v2": zoo_nets.mobilenet_v2,
        }
        if name in factories:
            return factories[name](classes=self.classes, input_shape=shape)
        raise ValueError(f"unknown model {self.model_name!r}")

    def predict_image_set(self, image_set: ImageSet, top_k: int = 5,
                          batch_size: int = 32):
        """Reference ``predictImageSet`` + LabelOutput: preprocess chain ->
        batched forward -> top-k (name, prob) per image."""
        transformed = image_set.transform(self.config.preprocessing())
        xs = transformed.to_feature_set().xs[0]
        probs = self.model.predict(xs, batch_size=batch_size)
        return LabelOutput(self.config.label_map, top_k)(probs)
