"""Image model zoo: classification + object detection (reference
zoo/.../models/image)."""
