"""SSD prior (anchor) boxes.

Reference: objectdetection/common/PriorBox generation used by the SSD-VGG
graph (reference ssd/SSDGraph.scala:56, ssd/SSD.scala:55-78).  Priors are a
*static* function of the feature-map geometry, so they are precomputed once
in numpy and baked into the jitted loss/postprocess as constants — no
per-step prior computation as in the reference's per-layer PriorBox modules.

Boxes are normalized to [0, 1], stored center-size ``(cx, cy, w, h)``.
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np


class PriorSpec:
    """One feature map's anchor config (reference ComponetParam in
    ssd/SSD.scala)."""

    def __init__(self, fm_size: int, min_size: float, max_size: float,
                 aspect_ratios: Sequence[float], step: float | None = None):
        self.fm_size = fm_size
        self.min_size = min_size
        self.max_size = max_size
        self.aspect_ratios = tuple(aspect_ratios)
        self.step = step

    @property
    def boxes_per_loc(self) -> int:
        # min, sqrt(min*max), and 2 per extra aspect ratio (ar, 1/ar)
        return 2 + 2 * len(self.aspect_ratios)


# SSD-300 VGG16 standard config: 38/19/10/5/3/1 maps, 8732 priors.
SSD300_SPECS = [
    PriorSpec(38, 30 / 300, 60 / 300, (2.0,)),
    PriorSpec(19, 60 / 300, 111 / 300, (2.0, 3.0)),
    PriorSpec(10, 111 / 300, 162 / 300, (2.0, 3.0)),
    PriorSpec(5, 162 / 300, 213 / 300, (2.0, 3.0)),
    PriorSpec(3, 213 / 300, 264 / 300, (2.0,)),
    PriorSpec(1, 264 / 300, 315 / 300, (2.0,)),
]


def generate_priors(specs: Sequence[PriorSpec]) -> np.ndarray:
    """(n_priors, 4) center-size normalized anchors."""
    out = []
    for spec in specs:
        f = spec.fm_size
        step = spec.step if spec.step is not None else 1.0 / f
        for i in range(f):
            for j in range(f):
                cx = (j + 0.5) * step
                cy = (i + 0.5) * step
                s = spec.min_size
                out.append([cx, cy, s, s])
                sp = math.sqrt(spec.min_size * spec.max_size)
                out.append([cx, cy, sp, sp])
                for ar in spec.aspect_ratios:
                    r = math.sqrt(ar)
                    out.append([cx, cy, s * r, s / r])
                    out.append([cx, cy, s / r, s * r])
    return np.clip(np.asarray(out, np.float32), 0.0, 1.0)


def center_to_corner(boxes):
    """(cx, cy, w, h) -> (xmin, ymin, xmax, ymax)."""
    boxes = np.asarray(boxes)
    half = 0.5 * boxes[..., 2:4]
    lo = boxes[..., 0:2] - half
    hi = boxes[..., 0:2] + half
    return np.concatenate([lo, hi], axis=-1)
