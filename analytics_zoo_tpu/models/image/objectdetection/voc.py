"""Pascal VOC dataset loading (reference
zoo/.../models/image/objectdetection/common/dataset/PascalVoc.scala:37-118
and Imdb.scala): VOCdevkit layout -> roi records for the SSD pipeline.

A roi record (see feature/image/roi.py): {"image": uint8 RGB HWC,
"boxes": (N,4) pixel corners, "classes": (N,) 1-based ids,
"difficult": (N,) 0/1, "path": str}.
"""

from __future__ import annotations

import os
import xml.etree.ElementTree as ET

import numpy as np

# PascalVoc.scala:80-88 — background is index 0; classes are 1-based.
VOC_CLASSES = (
    "__background__",
    "aeroplane", "bicycle", "bird", "boat",
    "bottle", "bus", "car", "cat", "chair",
    "cow", "diningtable", "dog", "horse",
    "motorbike", "person", "pottedplant",
    "sheep", "sofa", "train", "tvmonitor",
)
VOC_CLASS_TO_IND = {c: float(i) for i, c in enumerate(VOC_CLASSES)}


def load_voc_annotation(path: str, class_to_ind=None) -> dict:
    """Parse one Annotations/*.xml (PascalVoc.loadAnnotation,
    PascalVoc.scala:92-118)."""
    class_to_ind = class_to_ind or VOC_CLASS_TO_IND
    root = ET.parse(path).getroot()
    objs = root.findall("object")
    boxes = np.zeros((len(objs), 4), np.float32)
    classes = np.zeros((len(objs),), np.float32)
    difficult = np.zeros((len(objs),), np.float32)
    for i, obj in enumerate(objs):
        bb = obj.find("bndbox")
        boxes[i] = [float(bb.find(t).text)
                    for t in ("xmin", "ymin", "xmax", "ymax")]
        classes[i] = class_to_ind[obj.find("name").text.strip()]
        d = obj.find("difficult")
        difficult[i] = float(d.text) if d is not None else 0.0
    return {"boxes": boxes, "classes": classes, "difficult": difficult}


class PascalVoc:
    """VOCdevkit reader (PascalVoc.scala:37-76).

    ``devkit_path/VOC<year>/{ImageSets/Main/<image_set>.txt,
    Annotations/<idx>.xml, JPEGImages/<idx>.jpg}``.
    """

    def __init__(self, devkit_path: str, year: str = "2007",
                 image_set: str = "train", class_to_ind=None):
        if not os.path.isdir(devkit_path):
            raise FileNotFoundError(
                f"VOCdevkit path does not exist: {devkit_path}")
        self.devkit_path = devkit_path
        self.years = ["2007", "2012"] if year == "0712" else [year]
        self.image_set = image_set
        self.class_to_ind = class_to_ind or VOC_CLASS_TO_IND
        self.name = f"voc_{year}_{image_set}"

    def _index(self):
        out = []
        for y in self.years:
            data = os.path.join(self.devkit_path, "VOC" + y)
            lst = os.path.join(data, "ImageSets", "Main",
                               self.image_set + ".txt")
            with open(lst) as f:
                for line in f:
                    idx = line.split()[0].strip() if line.strip() else ""
                    if idx:
                        out.append((data, idx))
        return out

    @staticmethod
    def _read_image(path: str) -> np.ndarray:
        from PIL import Image

        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))

    def roidb(self, read_image: bool = True) -> list[dict]:
        """All records of the split (PascalVoc.getRoidb,
        PascalVoc.scala:53-76)."""
        records = []
        for data, idx in self._index():
            ann = load_voc_annotation(
                os.path.join(data, "Annotations", idx + ".xml"),
                self.class_to_ind)
            img_path = os.path.join(data, "JPEGImages", idx + ".jpg")
            rec = dict(ann, path=img_path)
            if read_image:
                rec["image"] = self._read_image(img_path)
            records.append(rec)
        return records
