"""COCO dataset loading (reference
zoo/.../models/image/objectdetection/common/dataset/Coco.scala): COCO
annotations -> roi records for the SSD pipeline.

Two layouts are supported:

- The reference's devkit layout (Coco.scala:40-51): ``ImageSets/<set>.txt``
  lines of ``<image_path> <annotation_path>`` with one per-image JSON of
  ``{"image": {...}, "annotation": [{bbox, category_id, area}, ...]}``.
- The standard ``instances_*.json`` single-file layout (what COCO actually
  distributes; the reference relies on external preprocessing to split it).
"""

from __future__ import annotations

import json
import os

import numpy as np

# Coco.scala:59-140 — 80 categories with the original sparse COCO ids;
# background first, class indices 1-based in devkit order.
COCO_CAT_ID_AND_CLASS = (
    (0, "__background__"),
    (1, "person"), (2, "bicycle"), (3, "car"), (4, "motorcycle"),
    (5, "airplane"), (6, "bus"), (7, "train"), (8, "truck"), (9, "boat"),
    (10, "traffic light"), (11, "fire hydrant"), (13, "stop sign"),
    (14, "parking meter"), (15, "bench"), (16, "bird"), (17, "cat"),
    (18, "dog"), (19, "horse"), (20, "sheep"), (21, "cow"),
    (22, "elephant"), (23, "bear"), (24, "zebra"), (25, "giraffe"),
    (27, "backpack"), (28, "umbrella"), (31, "handbag"), (32, "tie"),
    (33, "suitcase"), (34, "frisbee"), (35, "skis"), (36, "snowboard"),
    (37, "sports ball"), (38, "kite"), (39, "baseball bat"),
    (40, "baseball glove"), (41, "skateboard"), (42, "surfboard"),
    (43, "tennis racket"), (44, "bottle"), (46, "wine glass"),
    (47, "cup"), (48, "fork"), (49, "knife"), (50, "spoon"), (51, "bowl"),
    (52, "banana"), (53, "apple"), (54, "sandwich"), (55, "orange"),
    (56, "broccoli"), (57, "carrot"), (58, "hot dog"), (59, "pizza"),
    (60, "donut"), (61, "cake"), (62, "chair"), (63, "couch"),
    (64, "potted plant"), (65, "bed"), (67, "dining table"),
    (70, "toilet"), (72, "tv"), (73, "laptop"), (74, "mouse"),
    (75, "remote"), (76, "keyboard"), (77, "cell phone"),
    (78, "microwave"), (79, "oven"), (80, "toaster"), (81, "sink"),
    (82, "refrigerator"), (84, "book"), (85, "clock"), (86, "vase"),
    (87, "scissors"), (88, "teddy bear"), (89, "hair drier"),
    (90, "toothbrush"),
)
COCO_CLASSES = tuple(n for _, n in COCO_CAT_ID_AND_CLASS)
# sparse COCO category id -> dense 1-based class index (Coco.scala:144-146;
# background's id 0 maps to 1 there, foreground starts at 2 — here
# background stays 0 and foreground is 1..80, matching the VOC convention
# used by the rest of this detection stack)
COCO_CAT_ID_TO_IND = {
    cid: i for i, (cid, _) in enumerate(COCO_CAT_ID_AND_CLASS)
}


def _boxes_from_annotations(anns, width, height, cat_to_ind):
    """bbox [x, y, w, h] -> clipped corners; skip degenerate/zero-area
    (Coco.scala:148-176 semantics)."""
    boxes, classes, crowd = [], [], []
    for a in anns:
        if a.get("area", 1) <= 0:
            continue
        x, y, w, h = a["bbox"]
        x1 = max(0.0, x)
        y1 = max(0.0, y)
        # corners from the RAW origin so boxes crossing the left/top edge
        # are clipped, not shifted (x2 anchored at x, not at clipped x1)
        x2 = min(width - 1.0, x + max(0.0, w - 1))
        y2 = min(height - 1.0, y + max(0.0, h - 1))
        if x2 < x1 or y2 < y1:
            continue
        cid = int(a["category_id"])
        if cid not in cat_to_ind:
            continue
        boxes.append([x1, y1, x2, y2])
        classes.append(float(cat_to_ind[cid]))
        crowd.append(float(a.get("iscrowd", 0)))
    return (np.asarray(boxes, np.float32).reshape(-1, 4),
            np.asarray(classes, np.float32),
            np.asarray(crowd, np.float32))


def load_coco_annotation(path: str, cat_to_ind=None) -> dict:
    """One per-image annotation JSON (reference Coco.loadAnnotation,
    Coco.scala:148-186)."""
    cat_to_ind = cat_to_ind or COCO_CAT_ID_TO_IND
    with open(path) as f:
        doc = json.load(f)
    img = doc["image"]
    boxes, classes, crowd = _boxes_from_annotations(
        doc["annotation"], float(img["width"]), float(img["height"]),
        cat_to_ind)
    return {"boxes": boxes, "classes": classes, "difficult": crowd}


class Coco:
    """COCO reader with the reference's devkit layout (Coco.scala:39-51)
    or a standard ``instances_*.json``."""

    def __init__(self, devkit_path: str, image_set: str = "train",
                 instances_json: str | None = None, cat_to_ind=None):
        self.devkit_path = devkit_path
        self.image_set = image_set
        self.instances_json = instances_json
        self.cat_to_ind = cat_to_ind or COCO_CAT_ID_TO_IND
        self.name = f"coco_{image_set}"

    @staticmethod
    def _read_image(path: str) -> np.ndarray:
        from PIL import Image

        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))

    def roidb(self, read_image: bool = True) -> list[dict]:
        if self.instances_json:
            return self._from_instances(read_image)
        lst = os.path.join(self.devkit_path, "ImageSets",
                           self.image_set + ".txt")
        records = []
        with open(lst) as f:
            for line in f:
                if not line.strip():
                    continue
                img_rel, ann_rel = line.split()
                img_path = os.path.join(self.devkit_path, img_rel)
                ann = load_coco_annotation(
                    os.path.join(self.devkit_path, ann_rel),
                    self.cat_to_ind)
                rec = dict(ann, path=img_path)
                if read_image:
                    rec["image"] = self._read_image(img_path)
                records.append(rec)
        return records

    def _from_instances(self, read_image: bool) -> list[dict]:
        with open(self.instances_json) as f:
            doc = json.load(f)
        by_image: dict[int, list] = {}
        for a in doc.get("annotations", []):
            by_image.setdefault(a["image_id"], []).append(a)
        records = []
        for img in doc.get("images", []):
            boxes, classes, crowd = _boxes_from_annotations(
                by_image.get(img["id"], []), float(img["width"]),
                float(img["height"]), self.cat_to_ind)
            img_path = os.path.join(self.devkit_path, img["file_name"])
            rec = {"boxes": boxes, "classes": classes, "difficult": crowd,
                   "path": img_path}
            if read_image:
                rec["image"] = self._read_image(img_path)
            records.append(rec)
        return records
