"""Detection evaluation: (mean) average precision.

Reference: objectdetection/common/evaluation/MeanAveragePrecision.scala and
PascalVocEvaluator.scala — VOC-style AP with both the VOC2007 11-point
interpolation and the integral (area-under-PR) variant, matched at a
configurable IoU threshold, greedy one-gt-per-detection matching in score
order, optional ``use_difficult`` exclusion.
"""

from __future__ import annotations

import numpy as np


def _voc_ap(recall, precision, use_07_metric=False):
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = np.max(precision[recall >= t]) if np.any(recall >= t) else 0.0
            ap += p / 11.0
        return ap
    # integral AP: envelope then sum of rectangle areas
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    for i in range(len(mpre) - 2, -1, -1):
        mpre[i] = max(mpre[i], mpre[i + 1])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


def _iou_1_to_many(box, boxes):
    lo = np.maximum(box[0:2], boxes[:, 0:2])
    hi = np.minimum(box[2:4], boxes[:, 2:4])
    inter = np.prod(np.clip(hi - lo, 0, None), axis=1)
    union = (np.prod(box[2:4] - box[0:2])
             + np.prod(boxes[:, 2:4] - boxes[:, 0:2], axis=1) - inter)
    return np.where(union > 0, inter / union, 0.0)


def average_precision(detections, ground_truths, class_id: int,
                      iou_threshold=0.5, use_07_metric=False) -> float:
    """AP for one class.

    Args:
      detections: list per image of dicts (boxes, scores, classes).
      ground_truths: list per image of dicts (boxes, classes, optional
        difficult bool array).
    """
    # flatten detections of this class with image ids
    rows = []
    for img_id, det in enumerate(detections):
        sel = det["classes"] == class_id
        for box, score in zip(det["boxes"][sel], det["scores"][sel]):
            rows.append((score, img_id, box))
    rows.sort(key=lambda r: -r[0])

    gts, n_positive = {}, 0
    for img_id, gt in enumerate(ground_truths):
        sel = np.asarray(gt["classes"]) == class_id
        boxes = np.asarray(gt["boxes"], np.float32).reshape(-1, 4)[sel]
        difficult = np.asarray(
            gt.get("difficult", np.zeros(len(sel), bool)))[sel]
        gts[img_id] = (boxes, difficult, np.zeros(len(boxes), bool))
        n_positive += int((~difficult).sum())
    if n_positive == 0:
        # VOC convention: a class with no gt instances is excluded from mAP
        return float("nan")

    tp = np.zeros(len(rows))
    fp = np.zeros(len(rows))
    for i, (score, img_id, box) in enumerate(rows):
        boxes, difficult, used = gts[img_id]
        if len(boxes) == 0:
            fp[i] = 1
            continue
        ious = _iou_1_to_many(np.asarray(box, np.float32), boxes)
        j = int(np.argmax(ious))
        if ious[j] >= iou_threshold and not used[j]:
            if difficult[j]:
                continue  # neither tp nor fp (VOC convention)
            used[j] = True
            tp[i] = 1
        else:
            fp[i] = 1

    cum_tp = np.cumsum(tp)
    cum_fp = np.cumsum(fp)
    recall = cum_tp / n_positive
    precision = cum_tp / np.maximum(cum_tp + cum_fp, 1e-10)
    return _voc_ap(recall, precision, use_07_metric)


def mean_average_precision(detections, ground_truths, n_classes: int,
                           iou_threshold=0.5, use_07_metric=False) -> float:
    """mAP over classes (reference MeanAveragePrecision.scala)."""
    aps = [
        average_precision(detections, ground_truths, c, iou_threshold,
                          use_07_metric)
        for c in range(n_classes)
    ]
    aps = [a for a in aps if not np.isnan(a)]  # skip classes with no gt
    return float(np.mean(aps)) if aps else 0.0


class PascalVocEvaluator:
    """Reference PascalVocEvaluator.scala: per-class AP table + mAP with the
    VOC2007 11-point metric by default."""

    def __init__(self, class_names, iou_threshold=0.5, use_07_metric=True):
        self.class_names = list(class_names)
        self.iou_threshold = iou_threshold
        self.use_07_metric = use_07_metric

    def evaluate(self, detections, ground_truths):
        per_class = {
            name: average_precision(
                detections, ground_truths, c, self.iou_threshold,
                self.use_07_metric)
            for c, name in enumerate(self.class_names)
        }
        present = [a for a in per_class.values() if not np.isnan(a)]
        return {
            "AP": per_class,
            "mAP": float(np.mean(present)) if present else 0.0,
        }
