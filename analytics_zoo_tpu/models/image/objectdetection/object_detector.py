"""ObjectDetector — the detection zoo model.

Reference: objectdetection/ObjectDetector.scala (ZooModel subclass with
config-driven load + ``predictImageSet`` + label map) and the dataset padding
of SSDMiniBatch (variable gt counts -> fixed minibatch shapes).
"""

from __future__ import annotations

import numpy as np

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.models.image.objectdetection.multibox_loss import (
    MultiBoxLoss,
)
from analytics_zoo_tpu.models.image.objectdetection.postprocess import (
    detect,
    visualize,
)
from analytics_zoo_tpu.models.image.objectdetection.ssd import (
    ssd_tiny,
    ssd_vgg300,
)

PASCAL_CLASSES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


def pad_ground_truth(boxes_list, labels_list, max_boxes: int) -> np.ndarray:
    """Variable per-image gt -> fixed (B, max_boxes, 5) with label -1
    padding (the SSDMiniBatch role: static shapes for the jitted loss)."""
    b = len(boxes_list)
    out = np.zeros((b, max_boxes, 5), np.float32)
    out[..., 4] = -1.0
    for i, (boxes, labels) in enumerate(zip(boxes_list, labels_list)):
        boxes = np.asarray(boxes, np.float32).reshape(-1, 4)
        if len(boxes) > max_boxes:
            import logging

            logging.getLogger("analytics_zoo_tpu").warning(
                "image %d has %d gt boxes; only the first max_boxes=%d are "
                "kept — raise max_boxes for crowded datasets",
                i, len(boxes), max_boxes)
        boxes = boxes[:max_boxes]
        labels = np.asarray(labels, np.float32).reshape(-1)[:max_boxes]
        out[i, :len(boxes), :4] = boxes
        out[i, :len(labels), 4] = labels
    return out


class ObjectDetector(ZooModel):
    """SSD detector with training loss + postprocess wired in
    (reference ObjectDetector.scala + SSDGraph)."""

    def __init__(self, variant: str = "ssd-vgg16-300",
                 class_names=PASCAL_CLASSES, input_shape=None):
        self.variant = variant
        self.class_names = tuple(class_names)
        self.input_shape = input_shape
        super().__init__()

    def build_model(self):
        n = len(self.class_names)
        if self.variant == "ssd-vgg16-300":
            net, priors = ssd_vgg300(
                n, self.input_shape or (300, 300, 3))
        elif self.variant == "ssd-tiny":
            net, priors = ssd_tiny(n, self.input_shape or (64, 64, 3))
        else:
            raise ValueError(f"unknown variant {self.variant!r}")
        self.priors = priors
        return net

    def loss(self, **kwargs) -> MultiBoxLoss:
        return MultiBoxLoss(self.priors, len(self.class_names), **kwargs)

    def compile(self, optimizer, loss=None, metrics=None):
        self.model.compile(optimizer, loss or self.loss(), metrics)
        return self

    def fit_detection(self, images, boxes_list, labels_list, batch_size=8,
                      nb_epoch=1, max_boxes=16):
        y = pad_ground_truth(boxes_list, labels_list, max_boxes)
        self.model.fit(np.asarray(images, np.float32), y,
                       batch_size=batch_size, nb_epoch=nb_epoch)
        return self

    def predict_image_set(self, images, conf_threshold=0.5,
                          iou_threshold=0.45, top_k=200):
        """Reference ``predictImageSet``: raw forward + decode + NMS."""
        raw = self.model.predict(np.asarray(images, np.float32))
        return detect(raw, self.priors, conf_threshold, iou_threshold,
                      top_k)

    def visualize(self, image, detections, **kwargs):
        return visualize(image, detections, self.class_names, **kwargs)
