"""Detection postprocess: decode + per-class NMS + top-k.

Reference: the SSD DetectionOutput / NMS postprocess under
objectdetection/common (Scala, per-image mutable loops on CPU).

TPU split: box decoding and score softmax are jnp (batched, fused into the
inference program); NMS + top-k run on host numpy over the small decoded
set — the same division the reference uses (device math, host postprocess),
and the standard answer to NMS's data-dependent shapes under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.image.objectdetection.multibox_loss import (
    decode_boxes,
)


def decode_predictions(y_pred, priors_center, variances=(0.1, 0.2)):
    """(B, P, 4+C+1) raw output -> (boxes (B, P, 4) corner, scores
    (B, P, C+1) softmax).  jnp; jit/vmap-friendly."""
    loc = y_pred[..., :4]
    logits = y_pred[..., 4:]
    boxes = decode_boxes(loc, priors_center, variances)
    scores = jax.nn.softmax(logits, axis=-1)
    return boxes, scores


def nms_numpy(boxes: np.ndarray, scores: np.ndarray,
              iou_threshold: float = 0.45, top_k: int = 200) -> np.ndarray:
    """Greedy NMS; returns kept indices (host-side)."""
    order = np.argsort(-scores)[:top_k * 4]
    keep = []
    areas = np.prod(np.clip(boxes[:, 2:4] - boxes[:, 0:2], 0, None), axis=1)
    while order.size and len(keep) < top_k:
        i = order[0]
        keep.append(i)
        lo = np.maximum(boxes[i, 0:2], boxes[order[1:], 0:2])
        hi = np.minimum(boxes[i, 2:4], boxes[order[1:], 2:4])
        inter = np.prod(np.clip(hi - lo, 0, None), axis=1)
        union = areas[i] + areas[order[1:]] - inter
        iou = np.where(union > 0, inter / union, 0.0)
        order = order[1:][iou <= iou_threshold]
    return np.asarray(keep, np.int64)


def detect(y_pred, priors_center, conf_threshold=0.01, iou_threshold=0.45,
           top_k=200, variances=(0.1, 0.2)):
    """Full postprocess for a batch.

    Returns a list (length B) of dicts with ``boxes`` (N, 4) corner [0,1],
    ``scores`` (N,), ``classes`` (N,) zero-based (background removed) —
    the reference DetectionOutput format.
    """
    boxes, scores = decode_predictions(jnp.asarray(y_pred),
                                       jnp.asarray(priors_center), variances)
    boxes = np.asarray(boxes)
    scores = np.asarray(scores)
    results = []
    for b in range(boxes.shape[0]):
        all_boxes, all_scores, all_classes = [], [], []
        for c in range(1, scores.shape[-1]):          # skip background 0
            sc = scores[b, :, c]
            sel = sc > conf_threshold
            if not np.any(sel):
                continue
            idx = np.where(sel)[0]
            keep = nms_numpy(boxes[b, idx], sc[idx], iou_threshold, top_k)
            all_boxes.append(boxes[b, idx][keep])
            all_scores.append(sc[idx][keep])
            all_classes.append(np.full(len(keep), c - 1, np.int64))
        if all_boxes:
            bb = np.concatenate(all_boxes)
            ss = np.concatenate(all_scores)
            cc = np.concatenate(all_classes)
            order = np.argsort(-ss)[:top_k]
            results.append(dict(boxes=bb[order], scores=ss[order],
                                classes=cc[order]))
        else:
            results.append(dict(boxes=np.zeros((0, 4), np.float32),
                                scores=np.zeros((0,), np.float32),
                                classes=np.zeros((0,), np.int64)))
    return results


def visualize(image: np.ndarray, detections: dict, class_names=None,
              score_threshold=0.5) -> np.ndarray:
    """Draw boxes on an HWC uint8 image (reference Visualizer).  Pure
    numpy rectangle drawing; returns a copy."""
    img = np.asarray(image).copy()
    h, w = img.shape[:2]
    color = np.array([255, 64, 64], dtype=img.dtype)
    for box, score in zip(detections["boxes"], detections["scores"]):
        if score < score_threshold:
            continue
        x0 = int(np.clip(box[0] * w, 0, w - 1))
        y0 = int(np.clip(box[1] * h, 0, h - 1))
        x1 = int(np.clip(box[2] * w, 0, w - 1))
        y1 = int(np.clip(box[3] * h, 0, h - 1))
        img[y0:y1 + 1, x0] = color
        img[y0:y1 + 1, x1] = color
        img[y0, x0:x1 + 1] = color
        img[y1, x0:x1 + 1] = color
    return img
