"""Object detection stack (reference zoo/.../models/image/objectdetection):
SSD graphs, MultiBox loss, NMS postprocess, VOC mAP evaluation, the
ObjectDetector zoo model, and box visualization."""

from analytics_zoo_tpu.models.image.objectdetection.evaluation import (
    PascalVocEvaluator,
    average_precision,
    mean_average_precision,
)
from analytics_zoo_tpu.models.image.objectdetection.multibox_loss import (
    MultiBoxLoss,
    decode_boxes,
    encode_boxes,
    iou_matrix,
    match_priors,
)
from analytics_zoo_tpu.models.image.objectdetection.object_detector import (
    PASCAL_CLASSES,
    ObjectDetector,
    pad_ground_truth,
)
from analytics_zoo_tpu.models.image.objectdetection.postprocess import (
    detect,
    nms_numpy,
    visualize,
)
from analytics_zoo_tpu.models.image.objectdetection.priors import (
    PriorSpec,
    SSD300_SPECS,
    generate_priors,
)
from analytics_zoo_tpu.models.image.objectdetection.ssd import (
    ssd_tiny,
    ssd_vgg300,
)
from analytics_zoo_tpu.models.image.objectdetection.coco import (
    COCO_CAT_ID_TO_IND,
    COCO_CLASSES,
    Coco,
    load_coco_annotation,
)
from analytics_zoo_tpu.models.image.objectdetection.voc import (
    VOC_CLASS_TO_IND,
    VOC_CLASSES,
    PascalVoc,
    load_voc_annotation,
)

__all__ = [
    "ObjectDetector", "PASCAL_CLASSES", "pad_ground_truth",
    "MultiBoxLoss", "match_priors", "encode_boxes", "decode_boxes",
    "iou_matrix", "detect", "nms_numpy", "visualize",
    "average_precision", "mean_average_precision", "PascalVocEvaluator",
    "PriorSpec", "SSD300_SPECS", "generate_priors",
    "ssd_vgg300", "ssd_tiny",
    "PascalVoc", "VOC_CLASSES", "VOC_CLASS_TO_IND", "load_voc_annotation",
    "Coco", "COCO_CLASSES", "COCO_CAT_ID_TO_IND", "load_coco_annotation",
]
