"""MultiBox loss — matching, offset encoding, hard-negative mining.

Reference: objectdetection/common/loss/MultiBoxLoss.scala (smooth-L1 loc
loss on matched priors + softmax conf loss with 3:1 hard-negative mining).

TPU re-design: everything is static-shape jnp inside the jitted train step.
Ground truth arrives padded to ``max_boxes`` per image (label -1 = padding) —
the padding/bucketing answer to jit's static-shape regime called out in
SURVEY.md §7 hard-part 3.  Matching is vectorized IoU + argmax, with the
reference's *sequential bipartite* force-match re-expressed as a fixed-trip
``lax.fori_loop`` (each iteration claims the globally best unmatched
(prior, gt) pair), so every gt owns a distinct prior even when two gts share
the same best prior.  Hard-negative mining uses the rank-of-rank sort trick —
a fixed-shape replacement for the reference's per-image mutable heap.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.objectives import LossFunction


def iou_matrix(a_corner, b_corner):
    """Pairwise IoU: a (..., Na, 4), b (..., Nb, 4) corner boxes ->
    (..., Na, Nb)."""
    lo = jnp.maximum(a_corner[..., :, None, 0:2], b_corner[..., None, :, 0:2])
    hi = jnp.minimum(a_corner[..., :, None, 2:4], b_corner[..., None, :, 2:4])
    inter = jnp.prod(jnp.clip(hi - lo, 0.0), axis=-1)
    area_a = jnp.prod(a_corner[..., 2:4] - a_corner[..., 0:2], axis=-1)
    area_b = jnp.prod(b_corner[..., 2:4] - b_corner[..., 0:2], axis=-1)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def encode_boxes(matched_corner, priors_center, variances=(0.1, 0.2)):
    """gt corner boxes -> regression targets w.r.t. priors (SSD encoding)."""
    wh = matched_corner[..., 2:4] - matched_corner[..., 0:2]
    c = matched_corner[..., 0:2] + 0.5 * wh
    d_c = (c - priors_center[..., 0:2]) / (
        priors_center[..., 2:4] * variances[0])
    d_wh = jnp.log(jnp.clip(wh / priors_center[..., 2:4], 1e-8)) / \
        variances[1]
    return jnp.concatenate([d_c, d_wh], axis=-1)


def decode_boxes(loc, priors_center, variances=(0.1, 0.2)):
    """Regression outputs -> corner boxes (inverse of encode_boxes)."""
    c = priors_center[..., 0:2] + loc[..., 0:2] * variances[0] * \
        priors_center[..., 2:4]
    wh = priors_center[..., 2:4] * jnp.exp(loc[..., 2:4] * variances[1])
    lo = c - 0.5 * wh
    hi = c + 0.5 * wh
    return jnp.concatenate([lo, hi], axis=-1)


def _bipartite_force(iou, valid):
    """Sequential bipartite matching as a fixed-trip loop.

    Mirrors the reference's mutable bipartite pass (MultiBoxLoss.scala):
    repeat M times — claim the globally-best remaining (prior, gt) pair,
    then retire that prior row and gt column — so every valid gt gets its
    own prior even when two gts share the same best prior (plain argmax
    force-matching would drop one).  Returns a (P, M) force matrix with 2.0
    at the claimed pairs.
    """
    p, m = iou.shape
    work = jnp.where(valid[None, :], iou, -1.0)
    force = jnp.zeros_like(iou)

    def body(_, carry):
        work, force = carry
        idx = jnp.argmax(work)
        pi, gi = idx // m, idx % m
        ok = work[pi, gi] >= 0.0  # a still-unmatched valid gt remains
        force = jnp.where(ok, force.at[pi, gi].set(2.0), force)
        work = jnp.where(ok,
                         work.at[pi, :].set(-1.0).at[:, gi].set(-1.0), work)
        return work, force

    _, force = jax.lax.fori_loop(0, m, body, (work, force))
    return force


def match_priors(gt_corner, gt_labels, priors_corner, iou_threshold=0.5):
    """Per-image matching.

    Args:
      gt_corner: (max_boxes, 4) padded gt corner boxes.
      gt_labels: (max_boxes,) class ids in [0, C); -1 marks padding.
      priors_corner: (P, 4).

    Returns:
      (conf_target (P,) int32 with 0 = background and label+1 otherwise,
       matched_corner (P, 4) the gt box each prior regresses to).
    """
    valid = gt_labels >= 0
    iou = iou_matrix(priors_corner, gt_corner)          # (P, M)
    iou = jnp.where(valid[None, :], iou, -1.0)

    # force-match: bipartite pass gives each valid gt a distinct prior
    iou = jnp.maximum(iou, _bipartite_force(iou, valid))
    best_gt = jnp.argmax(iou, axis=1)                   # (P,)
    best_gt_iou = jnp.max(iou, axis=1)

    matched_corner = gt_corner[best_gt]                 # (P, 4)
    matched_label = gt_labels[best_gt]                  # (P,)
    positive = best_gt_iou >= iou_threshold
    conf_target = jnp.where(positive, matched_label + 1, 0).astype(jnp.int32)
    return conf_target, matched_corner


def _smooth_l1(x):
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


class MultiBoxLoss(LossFunction):
    """SSD loss over concatenated (loc, conf-logits) model output.

    ``y_pred``: (B, P, 4 + C+1) — 4 loc offsets then C+1 class logits
    (class 0 = background).  ``y_true``: (B, max_boxes, 5) rows of
    (xmin, ymin, xmax, ymax, label) with label -1 padding.

    Reference MultiBoxLoss.scala: loc smooth-L1 over positives + conf
    cross-entropy over positives and the top-(neg_pos_ratio x n_pos)
    hardest negatives, normalized by n_pos.
    """

    def __init__(self, priors: np.ndarray, n_classes: int,
                 iou_threshold=0.5, neg_pos_ratio=3.0,
                 variances=(0.1, 0.2), loc_weight=1.0):
        self.priors_center = jnp.asarray(priors)
        from analytics_zoo_tpu.models.image.objectdetection.priors import (
            center_to_corner,
        )

        self.priors_corner = jnp.asarray(center_to_corner(priors))
        self.n_classes = n_classes
        self.iou_threshold = iou_threshold
        self.neg_pos_ratio = neg_pos_ratio
        self.variances = variances
        self.loc_weight = loc_weight
        super().__init__(self._fn, "multibox")

    def _fn(self, y_true, y_pred):
        loc = y_pred[..., :4]                            # (B, P, 4)
        logits = y_pred[..., 4:]                         # (B, P, C+1)
        gt_boxes = y_true[..., :4]
        gt_labels = y_true[..., 4].astype(jnp.int32)

        conf_t, matched = jax.vmap(
            lambda b, l: match_priors(b, l, self.priors_corner,
                                      self.iou_threshold)
        )(gt_boxes, gt_labels)

        pos = conf_t > 0                                 # (B, P)
        n_pos = jnp.sum(pos, axis=1)                     # (B,)

        loc_t = encode_boxes(matched, self.priors_center, self.variances)
        loc_loss = jnp.sum(
            jnp.where(pos[..., None], _smooth_l1(loc - loc_t), 0.0),
            axis=(1, 2),
        )

        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.take_along_axis(logp, conf_t[..., None], axis=-1)[..., 0]

        # hard negative mining: per image rank negatives by ce descending;
        # keep rank < neg_pos_ratio * n_pos (rank-of-rank trick keeps shapes
        # static under jit)
        neg_ce = jnp.where(pos, -jnp.inf, ce)
        order = jnp.argsort(-neg_ce, axis=1)
        rank = jnp.argsort(order, axis=1)
        n_neg = jnp.minimum(
            (self.neg_pos_ratio * n_pos).astype(jnp.int32),
            jnp.sum(~pos, axis=1),
        )
        neg = rank < n_neg[:, None]
        conf_loss = jnp.sum(jnp.where(pos | neg, ce, 0.0), axis=1)

        denom = jnp.maximum(n_pos.astype(jnp.float32), 1.0)
        return (self.loc_weight * loc_loss + conf_loss) / denom
