"""SSD detection graphs.

Reference: ssd/SSDGraph.scala:56 (SSD-VGG16 graph: VGG base through conv5_3,
dilated fc6/fc7, extra feature layers conv8-11, per-map loc/conf heads,
conv4_3 L2 normalization with learnable scale) and ssd/SSD.scala:55-78
(per-map anchor params).

TPU re-design: the whole detector is one graph ``Model`` lowering to a
single XLA program — per-map heads are reshaped to (B, k·fm², ·) and
concatenated so the output is a dense (B, P, 4 + C+1) tensor (loc offsets ++
class logits); no per-layer PriorBox modules (priors are static numpy, see
priors.py).  All convs NHWC on the MXU.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.image.objectdetection.priors import (
    PriorSpec,
    SSD300_SPECS,
    generate_priors,
)
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.engine import Layer
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    AtrousConvolution2D,
    Convolution2D,
    MaxPooling2D,
    Merge,
    Reshape,
)


class L2Normalize2D(Layer):
    """Channel-wise L2 normalization with learnable per-channel scale
    (reference NormalizeScale on conv4_3 in SSDGraph.scala; init 20)."""

    def __init__(self, scale_init=20.0, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.scale_init = float(scale_init)
        self._config = dict(scale_init=self.scale_init)

    def build(self, input_shape):
        self.add_weight("scale", (int(input_shape[-1]),),
                        init=self.scale_init)

    def call(self, params, inputs, state=None, training=False, rng=None):
        norm = jnp.sqrt(
            jnp.sum(inputs * inputs, axis=-1, keepdims=True) + 1e-10)
        return inputs / norm * params["scale"]


def _conv_relu(x, filters, k, stride=1, pad="same", name=None, dilation=1):
    if dilation > 1:
        return AtrousConvolution2D(
            filters, k, k, atrous_rate=(dilation, dilation),
            border_mode=pad, activation="relu", name=name)(x)
    return Convolution2D(filters, k, k, subsample=(stride, stride),
                         border_mode=pad, activation="relu", name=name)(x)


def _vgg_base(x):
    """VGG16 through conv5_3 + dilated fc6/fc7; returns (conv4_3, fc7)."""
    for i, (n, reps) in enumerate([(64, 2), (128, 2), (256, 3)]):
        for j in range(reps):
            x = _conv_relu(x, n, 3, name=f"conv{i + 1}_{j + 1}")
        # pool3 uses SAME so 75 -> 38 (the reference's ceil-mode pooling)
        x = MaxPooling2D(pool_size=(2, 2),
                         border_mode="same" if i == 2 else "valid",
                         name=f"pool{i + 1}")(x)
    for j in range(3):
        x = _conv_relu(x, 512, 3, name=f"conv4_{j + 1}")
    conv4_3 = x
    x = MaxPooling2D(pool_size=(2, 2), name="pool4")(x)
    for j in range(3):
        x = _conv_relu(x, 512, 3, name=f"conv5_{j + 1}")
    x = MaxPooling2D(pool_size=(3, 3), strides=(1, 1), border_mode="same",
                     name="pool5")(x)
    x = _conv_relu(x, 1024, 3, dilation=6, name="fc6")
    fc7 = _conv_relu(x, 1024, 1, name="fc7")
    return conv4_3, fc7


def _extra_layers(x):
    """conv8-11 feature pyramids; returns the 4 extra maps."""
    maps = []
    x = _conv_relu(x, 256, 1, name="conv8_1")
    x = _conv_relu(x, 512, 3, stride=2, name="conv8_2")
    maps.append(x)                                     # 10x10
    x = _conv_relu(x, 128, 1, name="conv9_1")
    x = _conv_relu(x, 256, 3, stride=2, name="conv9_2")
    maps.append(x)                                     # 5x5
    x = _conv_relu(x, 128, 1, name="conv10_1")
    x = _conv_relu(x, 256, 3, pad="valid", name="conv10_2")
    maps.append(x)                                     # 3x3
    x = _conv_relu(x, 128, 1, name="conv11_1")
    x = _conv_relu(x, 256, 3, pad="valid", name="conv11_2")
    maps.append(x)                                     # 1x1
    return maps


def _detection_heads(feature_maps, specs, n_classes):
    """Per-map loc/conf 3x3 convs -> concat (B, P, 4 + C+1)."""
    locs, confs = [], []
    for i, (fm, spec) in enumerate(zip(feature_maps, specs)):
        k = spec.boxes_per_loc
        loc = Convolution2D(k * 4, 3, 3, border_mode="same",
                            name=f"loc_{i}")(fm)
        conf = Convolution2D(k * (n_classes + 1), 3, 3, border_mode="same",
                             name=f"conf_{i}")(fm)
        locs.append(Reshape((-1, 4), name=f"loc_flat_{i}")(loc))
        confs.append(
            Reshape((-1, n_classes + 1), name=f"conf_flat_{i}")(conf))
    loc_all = Merge(mode="concat", concat_axis=1, name="loc_concat")(locs)
    conf_all = Merge(mode="concat", concat_axis=1,
                     name="conf_concat")(confs)
    return Merge(mode="concat", concat_axis=-1,
                 name="predictions")([loc_all, conf_all])


def ssd_vgg300(n_classes: int = 20, input_shape=(300, 300, 3)):
    """Full SSD-300 VGG16 (reference SSDVGG graph).

    Returns (Model, priors (8732, 4) center-size numpy)."""
    inp = Input(shape=input_shape, name="image")
    conv4_3, fc7 = _vgg_base(inp)
    conv4_3 = L2Normalize2D(name="conv4_3_norm")(conv4_3)
    maps = [conv4_3, fc7] + _extra_layers(fc7)
    out = _detection_heads(maps, SSD300_SPECS, n_classes)
    return Model(inp, out), generate_priors(SSD300_SPECS)


def ssd_tiny(n_classes: int = 3, input_shape=(64, 64, 3)):
    """Small SSD for tests/toy data: 3 conv stages, 2 feature maps
    (8x8, 4x4).  Same head/loss/postprocess contract as ssd_vgg300."""
    specs = [
        PriorSpec(8, 0.15, 0.3, (2.0,)),
        PriorSpec(4, 0.3, 0.6, (2.0,)),
    ]
    inp = Input(shape=input_shape, name="image")
    x = _conv_relu(inp, 16, 3, name="t_conv1")
    x = MaxPooling2D()(x)                               # 32
    x = _conv_relu(x, 32, 3, name="t_conv2")
    x = MaxPooling2D()(x)                               # 16
    x = _conv_relu(x, 64, 3, name="t_conv3")
    x = MaxPooling2D()(x)                               # 8
    fm1 = _conv_relu(x, 64, 3, name="t_conv4")
    fm2 = _conv_relu(MaxPooling2D()(fm1), 64, 3, name="t_conv5")  # 4
    out = _detection_heads([fm1, fm2], specs, n_classes)
    return Model(inp, out), generate_priors(specs)
