"""Anomaly detection — reference
models/anomalydetection/AnomalyDetector.scala:40-72 (stacked-LSTM regressor)
plus its unroll/threshold utilities (Utils in the same package).
"""

from __future__ import annotations

import numpy as np

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    LSTM,
    Dense,
    Dropout,
)


class AnomalyDetector(ZooModel):
    """Stacked LSTMs → linear head predicting the next value
    (reference AnomalyDetector.scala:40-72: featureShape, hiddenLayers,
    dropouts)."""

    def __init__(self, feature_shape, hidden_layers=(8, 32, 15),
                 dropouts=(0.2, 0.2, 0.2)):
        self.feature_shape = tuple(feature_shape)
        self.hidden_layers = tuple(hidden_layers)
        self.dropouts = tuple(dropouts)
        assert len(self.hidden_layers) == len(self.dropouts)
        super().__init__()

    def build_model(self):
        model = Sequential(name="anomaly_detector")
        first = True
        for i, (width, drop) in enumerate(
                zip(self.hidden_layers, self.dropouts)):
            last = i == len(self.hidden_layers) - 1
            kwargs = dict(input_shape=self.feature_shape) if first else {}
            model.add(LSTM(width, return_sequences=not last,
                           name=f"lstm_{i}", **kwargs))
            model.add(Dropout(drop))
            first = False
        model.add(Dense(1, name="head"))
        return model

    # -- utilities (reference models/anomalydetection/Utils) ---------------
    @staticmethod
    def unroll(data, unroll_length: int):
        """Sliding windows: (N, F) series → x:(M, unroll, F), y:(M,) next
        first-feature value (reference Utils.unroll)."""
        data = np.asarray(data, dtype=np.float32)
        if data.ndim == 1:
            data = data[:, None]
        n = len(data) - unroll_length
        x = np.stack([data[i:i + unroll_length] for i in range(n)])
        y = data[unroll_length:, 0]
        return x, y

    @staticmethod
    def detect_anomalies(y_true, y_pred, anomaly_size: int = 5):
        """Top-``anomaly_size`` largest |error| points flagged as anomalies
        (reference AnomalyDetector.detectAnomalies)."""
        y_true = np.asarray(y_true).reshape(-1)
        y_pred = np.asarray(y_pred).reshape(-1)
        err = np.abs(y_true - y_pred)
        threshold = np.sort(err)[-min(anomaly_size, len(err))]
        flags = err >= threshold
        return [
            (float(t), float(p), bool(a))
            for t, p, a in zip(y_true, y_pred, flags)
        ]
