"""Model-zoo common base — reference models/common/ZooModel.scala:38-134
(save/load + predict plumbing) and common/Ranker.scala:33-109 (recallTopK /
NDCG evaluation for ranking models).
"""

from __future__ import annotations

import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.topology import KerasNet


class ZooModel:
    """Base for zoo models: wraps a built KerasNet and forwards the
    compile/fit/evaluate/predict/save surface (reference ZooModel.scala:38).

    Subclasses implement ``build_model() -> KerasNet`` and may add
    domain-specific helpers (e.g. ``recommend_for_user``).
    """

    def __init__(self):
        self.model: KerasNet = self.build_model()

    def build_model(self) -> KerasNet:
        raise NotImplementedError

    # -- forwarded surface -------------------------------------------------
    def compile(self, *args, **kwargs):
        self.model.compile(*args, **kwargs)
        return self

    def fit(self, *args, **kwargs):
        self.model.fit(*args, **kwargs)
        return self

    def evaluate(self, *args, **kwargs):
        return self.model.evaluate(*args, **kwargs)

    def predict(self, *args, **kwargs):
        return self.model.predict(*args, **kwargs)

    def predict_classes(self, *args, **kwargs):
        return self.model.predict_classes(*args, **kwargs)

    def set_tensorboard(self, *args, **kwargs):
        self.model.set_tensorboard(*args, **kwargs)

    def set_checkpoint(self, *args, **kwargs):
        self.model.set_checkpoint(*args, **kwargs)

    def summary(self):
        return self.model.summary()

    @property
    def params(self):
        return self.model.params

    def save_model(self, path, over_write=True):
        """Reference ZooModel.saveModel."""
        import pickle

        self.model.save(path, over_write=over_write)
        # append the wrapper class + config so load restores the subclass;
        # live nets (e.g. ImageClassifier(model=net)'s ``_provided``) are
        # nulled, not pickled — load_model reattaches ``model`` from the
        # saved KerasNet and never re-runs build_model
        with open(path + ".zoo_meta", "wb") as f:
            cfg = {k: (None if isinstance(v, KerasNet) else v)
                   for k, v in self.__dict__.items() if k != "model"}
            pickle.dump({"cls": type(self), "cfg": cfg}, f)

    @staticmethod
    def load_model(path):
        """Reference ZooModel.loadModel (models/common/ZooModel.scala)."""
        import os

        from analytics_zoo_tpu.common.safe_pickle import safe_load

        net = KerasNet.load(path)
        meta = path + ".zoo_meta"
        if os.path.exists(meta):
            with open(meta, "rb") as f:
                blob = safe_load(f)
            obj = blob["cls"].__new__(blob["cls"])
            obj.__dict__.update(blob["cfg"])
            obj.model = net
            return obj
        return net


class Ranker:
    """Ranking evaluation mixin — reference common/Ranker.scala:33-109:
    ``evaluateNDCG`` and ``evaluateMAP`` over grouped (query, candidates)
    relation lists."""

    @staticmethod
    def ndcg(y_true_groups, y_score_groups, k: int = 10) -> float:
        """Mean NDCG@k over groups (reference Ranker.evaluateNDCG)."""
        scores = []
        for rel, pred in zip(y_true_groups, y_score_groups):
            rel = np.asarray(rel, dtype=np.float64)
            pred = np.asarray(pred, dtype=np.float64)
            order = np.argsort(-pred)[:k]
            gains = (2.0 ** rel[order] - 1.0) / np.log2(
                np.arange(2, len(order) + 2)
            )
            ideal_order = np.argsort(-rel)[:k]
            ideal = (2.0 ** rel[ideal_order] - 1.0) / np.log2(
                np.arange(2, len(ideal_order) + 2)
            )
            denom = ideal.sum()
            scores.append(gains.sum() / denom if denom > 0 else 0.0)
        return float(np.mean(scores)) if scores else 0.0

    @staticmethod
    def recall_top_k(y_true_groups, y_score_groups, k: int = 10) -> float:
        """Fraction of relevant items recalled in the top-k
        (reference Ranker recallTopK semantics)."""
        scores = []
        for rel, pred in zip(y_true_groups, y_score_groups):
            rel = np.asarray(rel) > 0
            if rel.sum() == 0:
                continue
            order = np.argsort(-np.asarray(pred))[:k]
            scores.append(rel[order].sum() / rel.sum())
        return float(np.mean(scores)) if scores else 0.0

    @staticmethod
    def mean_average_precision(y_true_groups, y_score_groups,
                               threshold: float = 0.0) -> float:
        """Reference Ranker.evaluateMAP."""
        aps = []
        for rel, pred in zip(y_true_groups, y_score_groups):
            rel = np.asarray(rel) > threshold
            order = np.argsort(-np.asarray(pred))
            rel_sorted = rel[order]
            if rel_sorted.sum() == 0:
                continue
            precision = np.cumsum(rel_sorted) / np.arange(
                1, len(rel_sorted) + 1)
            aps.append((precision * rel_sorted).sum() / rel_sorted.sum())
        return float(np.mean(aps)) if aps else 0.0
