"""Seq2seq — RNN encoder/decoder with Bridge state adapters.

Reference: models/seq2seq/{RNNEncoder.scala:44, RNNDecoder.scala:45,
Bridge.scala:38, Seq2seq.scala}: stacked-RNN encoder, a Bridge mapping final
encoder states into decoder initial states, teacher-forced decoder for
training and a greedy ``infer`` loop for generation.

TPU re-design: teacher-forced training runs both stacks as fused lax.scans
in one jitted program; inference unrolls with ``lax.scan`` over the decoder
steps (static max length), so generation is also a single XLA program rather
than a per-step host loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer


def _lstm_step(params, h, c, x, ):
    z = x @ params["kernel"] + h @ params["recurrent_kernel"] \
        + params["bias"]
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = f * c + i * g
    h = o * jnp.tanh(c)
    return h, c


def _init_lstm(rng, in_dim, units):
    k1, k2 = jax.random.split(rng)
    glorot = jax.nn.initializers.glorot_uniform()
    return {
        "kernel": glorot(k1, (in_dim, 4 * units)),
        "recurrent_kernel": jax.nn.initializers.orthogonal()(
            k2, (units, 4 * units)),
        "bias": jnp.zeros((4 * units,)),
    }


class Seq2seq(Layer):
    """Encoder-decoder LSTM stack with embedding + Bridge
    (reference Seq2seq.scala factory: RNNEncoder(rnns) + Bridge +
    RNNDecoder(rnns) + generator head).

    Inputs: ``[encoder_tokens (B, Le), decoder_tokens (B, Ld)]`` (teacher
    forcing); output: (B, Ld, vocab) softmax.
    """

    def __init__(self, vocab_size, embed_dim=64, hidden_sizes=(128,),
                 bridge="pass", name=None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.vocab_size = int(vocab_size)
        self.embed_dim = int(embed_dim)
        self.hidden_sizes = tuple(hidden_sizes)
        assert bridge in ("pass", "dense")
        self.bridge = bridge

    def build(self, input_shape):
        pass

    def init_params(self, rng):
        ks = jax.random.split(rng, 4 + 2 * len(self.hidden_sizes))
        uniform = jax.nn.initializers.uniform(0.05)
        params = {
            "embed": uniform(ks[0], (self.vocab_size, self.embed_dim)),
            "enc": [], "dec": [],
            "head_kernel": jax.nn.initializers.glorot_uniform()(
                ks[1], (self.hidden_sizes[-1], self.vocab_size)),
            "head_bias": jnp.zeros((self.vocab_size,)),
        }
        in_dim = self.embed_dim
        for li, width in enumerate(self.hidden_sizes):
            params["enc"].append(_init_lstm(ks[2 + 2 * li], in_dim, width))
            params["dec"].append(
                _init_lstm(ks[3 + 2 * li], in_dim, width))
            in_dim = width
        if self.bridge == "dense":
            params["bridge"] = [
                {
                    "kernel": jax.nn.initializers.glorot_uniform()(
                        jax.random.fold_in(ks[-1], li), (2 * w, 2 * w)),
                    "bias": jnp.zeros((2 * w,)),
                }
                for li, w in enumerate(self.hidden_sizes)
            ]
        return params

    # -- encoder -----------------------------------------------------------
    def _encode(self, params, tokens):
        x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
        b = tokens.shape[0]
        states = []
        seq = jnp.swapaxes(x, 0, 1)
        for lp, width in zip(params["enc"], self.hidden_sizes):
            h0 = jnp.zeros((b, width))
            c0 = jnp.zeros((b, width))

            def body(carry, x_t, lp=lp):
                h, c = carry
                h, c = _lstm_step(lp, h, c, x_t)
                return (h, c), h

            (h, c), outs = lax.scan(body, (h0, c0), seq)
            states.append((h, c))
            seq = outs
        return states

    def _bridge(self, params, states):
        """Bridge: adapt encoder final states → decoder init states
        (reference Bridge.scala:38; 'pass' = passCurrState, 'dense' = dense
        transform of [h;c])."""
        if self.bridge == "pass":
            return states
        out = []
        for bp, (h, c) in zip(params["bridge"], states):
            hc = jnp.concatenate([h, c], axis=-1)
            hc = jnp.tanh(hc @ bp["kernel"] + bp["bias"])
            w = h.shape[-1]
            out.append((hc[:, :w], hc[:, w:]))
        return out

    # -- decoder -----------------------------------------------------------
    def _decode_teacher(self, params, states, tokens):
        x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
        seq = jnp.swapaxes(x, 0, 1)
        for lp, (h0, c0) in zip(params["dec"], states):
            def body(carry, x_t, lp=lp):
                h, c = carry
                h, c = _lstm_step(lp, h, c, x_t)
                return (h, c), h

            _, outs = lax.scan(body, (h0, c0), seq)
            seq = outs
        out = jnp.swapaxes(seq, 0, 1)
        logits = out @ params["head_kernel"] + params["head_bias"]
        return jax.nn.softmax(logits, axis=-1)

    def call(self, params, inputs, state=None, training=False, rng=None):
        enc_tokens, dec_tokens = inputs
        states = self._bridge(params, self._encode(params, enc_tokens))
        return self._decode_teacher(params, states, dec_tokens)

    def compute_output_shape(self, input_shape):
        enc, dec = input_shape
        return (dec[0], dec[1], self.vocab_size)

    def infer(self, params, enc_tokens, start_sign: int, max_len: int = 20,
              stop_sign: int | None = None):
        """Greedy generation (reference Seq2seq.infer): one jitted scan of
        ``max_len`` steps; stop_sign positions are masked post-hoc."""
        states = self._bridge(params, self._encode(
            params, jnp.asarray(enc_tokens)))
        b = np.shape(enc_tokens)[0]

        def step(carry, _):
            tok, layer_states = carry
            x = jnp.take(params["embed"], tok, axis=0)
            new_states = []
            for lp, (h, c) in zip(params["dec"], layer_states):
                h, c = _lstm_step(lp, h, c, x)
                new_states.append((h, c))
                x = h
            logits = x @ params["head_kernel"] + params["head_bias"]
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, new_states), nxt

        start = jnp.full((b,), start_sign, jnp.int32)
        _, toks = lax.scan(step, (start, states), None, length=max_len)
        toks = np.asarray(jnp.swapaxes(toks, 0, 1))
        if stop_sign is not None:
            for row in toks:
                stops = np.where(row == stop_sign)[0]
                if len(stops):
                    row[stops[0] + 1:] = stop_sign
        return toks
