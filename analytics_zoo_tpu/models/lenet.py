"""LeNet-5 — the reference's first-run example model
(pyzoo/zoo/examples LeNet MNIST; BASELINE.json config 1: "LeNet on MNIST via
zoo.pipeline.api.keras Sequential").
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Convolution2D,
    Dense,
    Flatten,
    MaxPooling2D,
)


def build_lenet(classes: int = 10, input_shape=(28, 28, 1)) -> Sequential:
    model = Sequential(name="lenet")
    model.add(Convolution2D(6, 5, 5, activation="tanh",
                            border_mode="same", input_shape=input_shape))
    model.add(MaxPooling2D())
    model.add(Convolution2D(16, 5, 5, activation="tanh"))
    model.add(MaxPooling2D())
    model.add(Flatten())
    model.add(Dense(120, activation="tanh"))
    model.add(Dense(84, activation="tanh"))
    model.add(Dense(classes, activation="softmax"))
    return model
