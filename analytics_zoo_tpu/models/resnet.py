"""ResNet — the headline image-classification model (BASELINE.md target:
ResNet-50 ImageNet images/sec/chip).

Reference: the SSD/ImageClassifier zoo ships ResNet-50 definitions and the
training example examples/resnet/TrainImageNet.scala:36-120 (SGD with linear
warmup + 0.1 decay at epochs 30/60/80, momentum 0.9, weight decay 1e-4,
label-smoothing option).  That example trains NCHW on MKL; this build is
NHWC bottleneck ResNet built on the graph Model API so the whole network
lowers to one XLA program of MXU convolutions.
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Activation,
    BatchNormalization,
    Convolution2D,
    Dense,
    GlobalAveragePooling2D,
    MaxPooling2D,
    Merge,
    SpaceToDepth,
)
from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
    SGD,
    warmup_epoch_decay,
)

_STAGES = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
}


def _conv_bn(x, filters, k, stride=1, name=None, activation=True):
    pad = "same"
    y = Convolution2D(filters, k, k, subsample=(stride, stride),
                      border_mode=pad, bias=False, init="he_normal",
                      name=None if name is None else f"{name}_conv")(x)
    y = BatchNormalization(
        name=None if name is None else f"{name}_bn")(y)
    if activation:
        y = Activation("relu")(y)
    return y


def _bottleneck(x, filters, stride, project, name):
    y = _conv_bn(x, filters, 1, stride, name=f"{name}_a")
    y = _conv_bn(y, filters, 3, 1, name=f"{name}_b")
    y = _conv_bn(y, 4 * filters, 1, 1, name=f"{name}_c", activation=False)
    if project:
        shortcut = _conv_bn(x, 4 * filters, 1, stride,
                            name=f"{name}_proj", activation=False)
    else:
        shortcut = x
    out = Merge(mode="sum", name=f"{name}_add")([y, shortcut])
    return Activation("relu")(out)


def _basic(x, filters, stride, project, name):
    y = _conv_bn(x, filters, 3, stride, name=f"{name}_a")
    y = _conv_bn(y, filters, 3, 1, name=f"{name}_b", activation=False)
    if project:
        shortcut = _conv_bn(x, filters, 1, stride, name=f"{name}_proj",
                            activation=False)
    else:
        shortcut = x
    out = Merge(mode="sum", name=f"{name}_add")([y, shortcut])
    return Activation("relu")(out)


class ResNet:
    """Factory namespace (reference zoo models expose companion-object
    factories)."""

    @staticmethod
    def image_net(depth: int = 50, classes: int = 1000,
                  input_shape=(224, 224, 3), stem: str = "7x7") -> Model:
        """ImageNet-scale ResNet (reference
        examples/resnet/TrainImageNet.scala model config).

        stem: "7x7" = the classic 7x7/s2 conv; "space_to_depth" = the TPU
        formulation (space-to-depth block 2 then 4x4/s1 conv on 12
        channels — an 8x8/s2 conv's kernel rearranged, so the MXU sees 12
        input channels unstrided instead of 3 strided; SAME-padding border
        geometry differs from the 7x7, so it is a train-from-scratch
        variant, not a checkpoint-compatible swap).  Same downstream
        network either way.
        """
        kind, stages = _STAGES[depth]
        block = _bottleneck if kind == "bottleneck" else _basic
        inp = Input(shape=input_shape, name="input")
        if stem == "space_to_depth":
            x = SpaceToDepth(2, name="stem_s2d")(inp)
            x = _conv_bn(x, 64, 4, stride=1, name="stem")
        elif stem == "7x7":
            x = _conv_bn(inp, 64, 7, stride=2, name="stem")
        else:
            raise ValueError(
                f"stem must be '7x7' or 'space_to_depth', got {stem!r}")
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                         border_mode="same")(x)
        filters = 64
        for si, blocks in enumerate(stages):
            for bi in range(blocks):
                stride = 2 if (si > 0 and bi == 0) else 1
                # bottleneck stage 0 needs a 64→256 projection; basic-block
                # stage 0 keeps the identity shortcut (standard ResNet-18/34)
                project = (bi == 0 and (si > 0 or kind == "bottleneck"))
                x = block(x, filters, stride, project,
                          name=f"res{si + 2}{chr(97 + bi)}")
            filters *= 2
        x = GlobalAveragePooling2D()(x)
        out = Dense(classes, activation="softmax", name="fc")(x)
        return Model(inp, out, name=f"resnet{depth}")

    @staticmethod
    def cifar(depth: int = 20, classes: int = 10) -> Model:
        """CIFAR ResNet (6n+2 layout; reference LocalEstimator ResNet
        example trains this shape on thread pools)."""
        n = (depth - 2) // 6
        inp = Input(shape=(32, 32, 3), name="input")
        x = _conv_bn(inp, 16, 3, 1, name="stem")
        filters = 16
        for si in range(3):
            for bi in range(n):
                stride = 2 if (si > 0 and bi == 0) else 1
                x = _basic(x, filters, stride, project=(bi == 0 and si > 0),
                           name=f"res{si + 2}{chr(97 + bi)}")
            filters *= 2
        x = GlobalAveragePooling2D()(x)
        out = Dense(classes, activation="softmax", name="fc")(x)
        return Model(inp, out, name=f"resnet{depth}_cifar")

    @staticmethod
    def imagenet_optimizer(base_lr=0.1, batch_size=256, steps_per_epoch=5004,
                           warmup_epochs=5, momentum=0.9,
                           weight_decay=1e-4) -> SGD:
        """The TrainImageNet.scala recipe: linear warmup then 0.1 decay at
        epochs 30/60/80 (TrainImageNet.scala:36-120), momentum 0.9, decoupled
        weight decay."""
        sched = warmup_epoch_decay(
            warmup_steps=warmup_epochs * steps_per_epoch,
            steps_per_epoch=steps_per_epoch,
            boundaries_epochs=(30, 60, 80),
            decay=0.1,
        )
        return SGD(lr=base_lr * batch_size / 256.0, momentum=momentum,
                   weight_decay=weight_decay, schedule=sched)
