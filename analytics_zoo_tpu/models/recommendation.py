"""Recommendation models — NeuralCF, WideAndDeep, SessionRecommender.

Reference: zoo/.../models/recommendation/{NeuralCF.scala:45-105,
WideAndDeep.scala:101-275, SessionRecommender.scala:45-158, Recommender.scala
(recommendForUser/recommendForItem base)}.
"""

from __future__ import annotations

import numpy as np

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    GRU,
    Dense,
    Embedding,
    Flatten,
    Merge,
)


class Recommender(ZooModel):
    """Base with candidate-scoring helpers (reference Recommender.scala:
    ``recommendForUser`` / ``recommendForItem``)."""

    def predict_user_item_pair(self, user_item_pairs, batch_size=1024):
        """Score (user, item) id pairs → probability of positive class."""
        pairs = np.asarray(user_item_pairs)
        probs = self.predict([pairs[:, 0], pairs[:, 1]],
                             batch_size=batch_size)
        probs = np.asarray(probs)
        return probs[:, -1] if probs.ndim == 2 and probs.shape[1] > 1 \
            else probs.reshape(-1)

    def recommend_for_user(self, user_id, candidate_items, max_items=5,
                           batch_size=1024):
        items = np.asarray(candidate_items)
        pairs = np.stack([np.full_like(items, user_id), items], axis=1)
        scores = self.predict_user_item_pair(pairs, batch_size)
        order = np.argsort(-scores)[:max_items]
        return [(int(items[i]), float(scores[i])) for i in order]

    def recommend_for_item(self, item_id, candidate_users, max_users=5,
                           batch_size=1024):
        users = np.asarray(candidate_users)
        pairs = np.stack([users, np.full_like(users, item_id)], axis=1)
        scores = self.predict_user_item_pair(pairs, batch_size)
        order = np.argsort(-scores)[:max_users]
        return [(int(users[i]), float(scores[i])) for i in order]


class NeuralCF(Recommender):
    """Neural Collaborative Filtering (reference NeuralCF.scala:45-105):
    GMF (elementwise product of user/item embeddings) merged with an MLP
    tower over concatenated embeddings; ``include_mf`` toggles the GMF arm.
    Inputs: [user_ids, item_ids] (0-based; the reference is 1-based Scala)."""

    def __init__(self, user_count, item_count, class_num=2, user_embed=20,
                 item_embed=20, hidden_layers=(40, 20, 10), include_mf=True,
                 mf_embed=20):
        self.user_count = int(user_count)
        self.item_count = int(item_count)
        self.class_num = int(class_num)
        self.user_embed = int(user_embed)
        self.item_embed = int(item_embed)
        self.hidden_layers = tuple(hidden_layers)
        self.include_mf = include_mf
        self.mf_embed = int(mf_embed)
        super().__init__()

    def build_model(self):
        user = Input(shape=(), name="user_input")
        item = Input(shape=(), name="item_input")

        mlp_u = Embedding(self.user_count, self.user_embed,
                          name="mlp_user_embed")(user)
        mlp_i = Embedding(self.item_count, self.item_embed,
                          name="mlp_item_embed")(item)
        h = Merge(mode="concat", concat_axis=-1)([mlp_u, mlp_i])
        for i, width in enumerate(self.hidden_layers):
            h = Dense(width, activation="relu", name=f"mlp_{i}")(h)

        if self.include_mf:
            mf_u = Embedding(self.user_count, self.mf_embed,
                             name="mf_user_embed")(user)
            mf_i = Embedding(self.item_count, self.mf_embed,
                             name="mf_item_embed")(item)
            mf = Merge(mode="mul")([mf_u, mf_i])
            h = Merge(mode="concat", concat_axis=-1)([h, mf])
        out = Dense(self.class_num, activation="softmax", name="head")(h)
        return Model([user, item], out, name="neural_cf")


class ColumnFeatureInfo:
    """Reference recommendation/Utils ColumnFeatureInfo: declares which
    dataframe columns feed the wide / indicator / embedding / continuous
    parts of WideAndDeep."""

    def __init__(self, wide_base_cols=(), wide_base_dims=(),
                 wide_cross_cols=(), wide_cross_dims=(),
                 indicator_cols=(), indicator_dims=(),
                 embed_cols=(), embed_in_dims=(), embed_out_dims=(),
                 continuous_cols=()):
        self.wide_base_cols = list(wide_base_cols)
        self.wide_base_dims = list(wide_base_dims)
        self.wide_cross_cols = list(wide_cross_cols)
        self.wide_cross_dims = list(wide_cross_dims)
        self.indicator_cols = list(indicator_cols)
        self.indicator_dims = list(indicator_dims)
        self.embed_cols = list(embed_cols)
        self.embed_in_dims = list(embed_in_dims)
        self.embed_out_dims = list(embed_out_dims)
        self.continuous_cols = list(continuous_cols)

    @property
    def wide_dim(self):
        return sum(self.wide_base_dims) + sum(self.wide_cross_dims)

    @property
    def indicator_dim(self):
        return sum(self.indicator_dims)


class WideAndDeep(Recommender):
    """Wide & Deep (reference WideAndDeep.scala:101-275): a wide sparse
    linear arm over one-hot/cross features plus a deep MLP over embedded
    categorical + indicator + continuous features.

    Inputs (dense re-encoding of the reference's SparseTensor wide input):
    ``[wide_multi_hot, indicators, embed_ids, continuous]`` — build them with
    :func:`to_wide_deep_features`.
    """

    def __init__(self, model_type="wide_n_deep", class_num=2,
                 column_info: ColumnFeatureInfo | None = None,
                 hidden_layers=(40, 20, 10)):
        assert model_type in ("wide", "deep", "wide_n_deep")
        self.model_type = model_type
        self.class_num = int(class_num)
        self.column_info = column_info or ColumnFeatureInfo()
        self.hidden_layers = tuple(hidden_layers)
        super().__init__()

    def build_model(self):
        info = self.column_info
        inputs, arms = [], []

        if self.model_type in ("wide", "wide_n_deep"):
            wide = Input(shape=(info.wide_dim,), name="wide_input")
            inputs.append(wide)
            arms.append(Dense(self.class_num, bias=False,
                              name="wide_linear")(wide))

        if self.model_type in ("deep", "wide_n_deep"):
            deep_parts = []
            if info.indicator_dim:
                ind = Input(shape=(info.indicator_dim,),
                            name="indicator_input")
                inputs.append(ind)
                deep_parts.append(ind)
            embed_vars = []
            if info.embed_cols:
                ids = Input(shape=(len(info.embed_cols),),
                            name="embed_input")
                inputs.append(ids)
                for i, (col, in_dim, out_dim) in enumerate(zip(
                        info.embed_cols, info.embed_in_dims,
                        info.embed_out_dims)):
                    from analytics_zoo_tpu.pipeline.api.autograd import (
                        LambdaOp,
                    )
                    import jax.numpy as jnp

                    pick = LambdaOp(
                        (lambda idx: (lambda v: v[:, idx].astype(
                            jnp.int32)))(i),
                        (lambda s: (s[0],)), op_name=f"pick_{col}",
                    )(ids)
                    emb = Embedding(in_dim + 1, out_dim,
                                    name=f"embed_{col}")(pick)
                    embed_vars.append(emb)
            deep_parts.extend(embed_vars)
            if info.continuous_cols:
                cont = Input(shape=(len(info.continuous_cols),),
                             name="continuous_input")
                inputs.append(cont)
                deep_parts.append(cont)
            h = deep_parts[0] if len(deep_parts) == 1 else Merge(
                mode="concat", concat_axis=-1)(deep_parts)
            for i, width in enumerate(self.hidden_layers):
                h = Dense(width, activation="relu", name=f"deep_{i}")(h)
            arms.append(Dense(self.class_num, name="deep_head")(h))

        merged = arms[0] if len(arms) == 1 else Merge(mode="sum")(arms)
        from analytics_zoo_tpu.pipeline.api.keras.layers import Activation

        out = Activation("softmax")(merged)
        return Model(inputs, out, name=self.model_type)

    def predict_user_item_pair(self, features, batch_size=1024):
        probs = np.asarray(self.predict(features, batch_size=batch_size))
        return probs[:, -1]

    def recommend_for_user(self, *args, **kwargs):
        raise NotImplementedError(
            "WideAndDeep scores feature rows, not raw (user, item) ids — "
            "build inputs with to_wide_deep_features and call "
            "predict_user_item_pair (matches the reference, which joins "
            "features per candidate before scoring)"
        )

    def recommend_for_item(self, *args, **kwargs):
        raise NotImplementedError(
            "WideAndDeep scores feature rows; see recommend_for_user"
        )


def to_wide_deep_features(rows: dict, info: ColumnFeatureInfo):
    """Encode a columnar dict of arrays into WideAndDeep inputs (role of
    reference Utils.getWideTensor/getDeepTensor)."""
    n = len(next(iter(rows.values())))
    outs = []
    if info.wide_dim:
        wide = np.zeros((n, info.wide_dim), np.float32)
        offset = 0
        for col, dim in zip(info.wide_base_cols + info.wide_cross_cols,
                            info.wide_base_dims + info.wide_cross_dims):
            idx = np.asarray(rows[col]).astype(np.int64) % dim
            wide[np.arange(n), offset + idx] = 1.0
            offset += dim
        outs.append(wide)
    if info.indicator_dim:
        ind = np.zeros((n, info.indicator_dim), np.float32)
        offset = 0
        for col, dim in zip(info.indicator_cols, info.indicator_dims):
            idx = np.asarray(rows[col]).astype(np.int64) % dim
            ind[np.arange(n), offset + idx] = 1.0
            offset += dim
        outs.append(ind)
    if info.embed_cols:
        outs.append(np.stack(
            [np.asarray(rows[c]) for c in info.embed_cols], axis=1
        ).astype(np.float32))
    if info.continuous_cols:
        outs.append(np.stack(
            [np.asarray(rows[c]) for c in info.continuous_cols], axis=1
        ).astype(np.float32))
    return outs


class SessionRecommender(Recommender):
    """Session-based recommender (reference SessionRecommender.scala:45-158):
    embedded session item sequence → GRU stack → softmax over items;
    optionally a second arm over longer purchase history."""

    def __init__(self, item_count, item_embed=100, rnn_hidden_layers=(40, 20),
                 session_length=5, include_history=False, mlp_hidden_layers=(40, 20),
                 history_length=10):
        self.item_count = int(item_count)
        self.item_embed = int(item_embed)
        self.rnn_hidden_layers = tuple(rnn_hidden_layers)
        self.session_length = int(session_length)
        self.include_history = include_history
        self.mlp_hidden_layers = tuple(mlp_hidden_layers)
        self.history_length = int(history_length)
        super().__init__()

    def build_model(self):
        session = Input(shape=(self.session_length,), name="session_input")
        h = Embedding(self.item_count + 1, self.item_embed,
                      name="session_embed")(session)
        for i, width in enumerate(self.rnn_hidden_layers[:-1]):
            h = GRU(width, return_sequences=True, name=f"gru_{i}")(h)
        h = GRU(self.rnn_hidden_layers[-1], name="gru_last")(h)
        inputs = [session]
        if self.include_history:
            hist = Input(shape=(self.history_length,), name="history_input")
            inputs.append(hist)
            g = Embedding(self.item_count + 1, self.item_embed,
                          name="history_embed")(hist)
            g = Flatten()(g)
            for i, width in enumerate(self.mlp_hidden_layers):
                g = Dense(width, activation="relu", name=f"mlp_{i}")(g)
            h = Merge(mode="concat", concat_axis=-1)([h, g])
        out = Dense(self.item_count + 1, activation="softmax",
                    name="item_head")(h)
        return Model(inputs, out, name="session_recommender")

    def recommend_for_session(self, sessions, max_items=5, batch_size=1024):
        probs = np.asarray(self.predict(sessions, batch_size=batch_size))
        top = np.argsort(-probs, axis=1)[:, :max_items]
        return [
            [(int(i), float(p[i])) for i in row]
            for row, p in zip(top, probs)
        ]
