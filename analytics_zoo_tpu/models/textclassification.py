"""Text classification — reference
models/textclassification/TextClassifier.scala:34-109: embedding +
{CNN | LSTM | GRU} encoder + dense softmax head.
"""

from __future__ import annotations

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    GRU,
    LSTM,
    Convolution1D,
    Dense,
    Dropout,
    Embedding,
    GlobalMaxPooling1D,
)


class TextClassifier(ZooModel):
    """Reference TextClassifier(classNum, tokenLength, sequenceLength,
    encoder, encoderOutputDim) — encoder in {"cnn", "lstm", "gru"}."""

    def __init__(self, class_num, token_length, sequence_length=500,
                 encoder="cnn", encoder_output_dim=256, vocab_size=20000,
                 embedding_weights=None, train_embed=True):
        self.class_num = int(class_num)
        self.token_length = int(token_length)
        self.sequence_length = int(sequence_length)
        self.encoder = encoder.lower()
        self.encoder_output_dim = int(encoder_output_dim)
        self.vocab_size = int(vocab_size)
        self.embedding_weights = embedding_weights
        self.train_embed = train_embed
        super().__init__()

    def build_model(self):
        model = Sequential(name="text_classifier")
        model.add(Embedding(self.vocab_size, self.token_length,
                            weights=self.embedding_weights,
                            trainable=self.train_embed,
                            input_shape=(self.sequence_length,),
                            name="embedding"))
        if self.encoder == "cnn":
            model.add(Convolution1D(self.encoder_output_dim, 5,
                                    activation="relu", name="conv"))
            model.add(GlobalMaxPooling1D())
        elif self.encoder == "lstm":
            model.add(LSTM(self.encoder_output_dim, name="lstm"))
        elif self.encoder == "gru":
            model.add(GRU(self.encoder_output_dim, name="gru"))
        else:
            raise ValueError(f"unknown encoder {self.encoder!r}")
        model.add(Dropout(0.2))
        model.add(Dense(128, activation="relu", name="fc1"))
        model.add(Dense(self.class_num, activation="softmax", name="head"))
        return model
