"""Built-in model zoo (reference zoo/src/.../models/)."""

from analytics_zoo_tpu.models.anomalydetection import (  # noqa: F401
    AnomalyDetector,
)
from analytics_zoo_tpu.models.common import Ranker, ZooModel  # noqa: F401
from analytics_zoo_tpu.models.inception import Inception  # noqa: F401
from analytics_zoo_tpu.models.lenet import build_lenet  # noqa: F401
from analytics_zoo_tpu.models.recommendation import (  # noqa: F401
    ColumnFeatureInfo,
    NeuralCF,
    Recommender,
    SessionRecommender,
    WideAndDeep,
    to_wide_deep_features,
)
from analytics_zoo_tpu.models.resnet import ResNet  # noqa: F401
from analytics_zoo_tpu.models.seq2seq import Seq2seq  # noqa: F401
from analytics_zoo_tpu.models.textclassification import (  # noqa: F401
    TextClassifier,
)
from analytics_zoo_tpu.models.textmatching import KNRM  # noqa: F401
