"""Text matching — KNRM kernel-pooling ranking model.

Reference: models/textmatching/KNRM.scala:60-106: query/doc embeddings →
cosine translation matrix → RBF kernel pooling over ``kernelNum`` kernels
(mu from 1.0 down in 0.1 steps, sigma 0.1 / exactMatch 0.001) → log-sum →
dense sigmoid score.  Pairs with the RankHinge loss and Ranker NDCG/MAP
evaluation (common/Ranker.scala).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from analytics_zoo_tpu.models.common import Ranker, ZooModel
from analytics_zoo_tpu.pipeline.api.autograd import LambdaOp, batch_dot
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Embedding


class KNRM(ZooModel, Ranker):
    def __init__(self, text1_length, text2_length, vocab_size=20000,
                 embed_size=300, embed_weights=None, train_embed=True,
                 kernel_num=21, sigma=0.1, exact_sigma=0.001,
                 target_mode="ranking"):
        self.text1_length = int(text1_length)
        self.text2_length = int(text2_length)
        self.vocab_size = int(vocab_size)
        self.embed_size = int(embed_size)
        self.embed_weights = embed_weights
        self.train_embed = train_embed
        if int(kernel_num) < 2:
            raise ValueError("kernel_num must be >= 2 (kernel mus span "
                             "[1.0, -1.0] in 2/(kernel_num-1) steps)")
        self.kernel_num = int(kernel_num)
        self.sigma = float(sigma)
        self.exact_sigma = float(exact_sigma)
        self.target_mode = target_mode
        super().__init__()

    def build_model(self):
        q = Input(shape=(self.text1_length,), name="query")
        d = Input(shape=(self.text2_length,), name="doc")
        embed = Embedding(self.vocab_size, self.embed_size,
                          weights=self.embed_weights,
                          trainable=self.train_embed, name="embedding")
        qe = embed(q)
        de = embed(d)
        # cosine translation matrix (B, Lq, Ld)
        mm = batch_dot(qe, de, axes=(2, 2), normalize=True)

        kernel_num, sigma, exact_sigma = (
            self.kernel_num, self.sigma, self.exact_sigma
        )

        def kernel_pool(sim):
            feats = []
            for i in range(kernel_num):
                mu = 1.0 - i * (2.0 / (kernel_num - 1))
                s = exact_sigma if mu > 1.0 - 1e-6 else sigma
                k = jnp.exp(-((sim - mu) ** 2) / (2.0 * s * s))
                # sum over doc terms, log, sum over query terms
                kq = jnp.log(
                    jnp.clip(jnp.sum(k, axis=2), 1e-10)
                ) * 0.01
                feats.append(jnp.sum(kq, axis=1))
            return jnp.stack(feats, axis=1)

        pooled = LambdaOp(
            kernel_pool, lambda s: (s[0], kernel_num), op_name="kernel_pool"
        )(mm)
        if self.target_mode == "ranking":
            out = Dense(1, name="score")(pooled)
        else:
            out = Dense(1, activation="sigmoid", name="score")(pooled)
        return Model([q, d], out, name="knrm")

    def evaluate_ndcg(self, grouped_qd, grouped_labels, k=10,
                      batch_size=1024):
        """Reference Ranker.evaluateNDCG over relation lists."""
        scores = [
            np.asarray(self.predict([np.asarray(g[0]), np.asarray(g[1])],
                                    batch_size=batch_size)).reshape(-1)
            for g in grouped_qd
        ]
        return self.ndcg(grouped_labels, scores, k)

    def evaluate_map(self, grouped_qd, grouped_labels, batch_size=1024):
        scores = [
            np.asarray(self.predict([np.asarray(g[0]), np.asarray(g[1])],
                                    batch_size=batch_size)).reshape(-1)
            for g in grouped_qd
        ]
        return self.mean_average_precision(grouped_labels, scores)
