"""The remaining image-classification zoo families (reference
``ImageClassificationConfig.scala:31-50`` model set: alexnet, vgg-16/19,
densenet-161, squeezenet, mobilenet, mobilenet-v2 — inception-v1 and
resnet-50 live in their own modules).  The ``*-quantize``/``*-int8``
variants of the reference map to ``InferenceModel.optimize("int8")``
(weight/activation quantization is a deployment pass here, not a separate
graph).

All builders take ``classes``/``input_shape`` plus a width/depth knob so
CI exercises the exact block structure at toy scale; defaults match the
canonical papers' filter plans (channels-last NHWC throughout — the TPU
layout; the reference is NCHW Torch-style).
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import Input, Model, Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Activation,
    AveragePooling2D,
    BatchNormalization,
    Convolution2D,
    Dense,
    DepthwiseConvolution2D,
    Dropout,
    Flatten,
    GlobalAveragePooling2D,
    MaxPooling2D,
)
from analytics_zoo_tpu.pipeline.api.keras.layers.merge import Merge


def _concat(tensors, name=None):
    return Merge(mode="concat", concat_axis=-1, name=name)(tensors)


# ---------------------------------------------------------------------------
# AlexNet (reference alexnet config; Krizhevsky 2012 filter plan)
# ---------------------------------------------------------------------------

def alexnet(classes: int = 1000, input_shape=(227, 227, 3),
            width: float = 1.0, has_dropout: bool = True) -> Sequential:
    def c(ch):
        return max(int(ch * width), 4)

    from analytics_zoo_tpu.pipeline.api.keras.layers import LRN2D

    m = Sequential(name="alexnet")
    m.add(Convolution2D(c(96), 11, 11, subsample=(4, 4), activation="relu",
                        input_shape=input_shape, name="conv1"))
    m.add(MaxPooling2D((3, 3), strides=(2, 2), name="pool1"))
    # LRN placement matches the reference net (bvlc_alexnet: norm1/norm2
    # after the first two pooling stages)
    m.add(LRN2D(alpha=1e-4, k=1.0, beta=0.75, n=5, name="norm1"))
    m.add(Convolution2D(c(256), 5, 5, border_mode="same",
                        activation="relu", name="conv2"))
    m.add(MaxPooling2D((3, 3), strides=(2, 2), name="pool2"))
    m.add(LRN2D(alpha=1e-4, k=1.0, beta=0.75, n=5, name="norm2"))
    m.add(Convolution2D(c(384), 3, 3, border_mode="same",
                        activation="relu", name="conv3"))
    m.add(Convolution2D(c(384), 3, 3, border_mode="same",
                        activation="relu", name="conv4"))
    m.add(Convolution2D(c(256), 3, 3, border_mode="same",
                        activation="relu", name="conv5"))
    m.add(MaxPooling2D((3, 3), strides=(2, 2), name="pool5"))
    m.add(Flatten(name="flatten"))
    m.add(Dense(c(4096), activation="relu", name="fc6"))
    if has_dropout:
        m.add(Dropout(0.5, name="drop6"))
    m.add(Dense(c(4096), activation="relu", name="fc7"))
    if has_dropout:
        m.add(Dropout(0.5, name="drop7"))
    m.add(Dense(classes, activation="softmax", name="fc8"))
    return m


# ---------------------------------------------------------------------------
# VGG-16 / VGG-19 (reference vgg-16/vgg-19 configs; Simonyan 2014 plan D/E)
# ---------------------------------------------------------------------------

_VGG_PLANS = {
    16: (2, 2, 3, 3, 3),
    19: (2, 2, 4, 4, 4),
}


def vgg(depth: int = 16, classes: int = 1000, input_shape=(224, 224, 3),
        width: float = 1.0, has_dropout: bool = True) -> Sequential:
    if depth not in _VGG_PLANS:
        raise ValueError(f"vgg depth must be one of {sorted(_VGG_PLANS)}")

    def c(ch):
        return max(int(ch * width), 4)

    m = Sequential(name=f"vgg_{depth}")
    first = True
    for block, (n_convs, ch) in enumerate(
            zip(_VGG_PLANS[depth], (64, 128, 256, 512, 512)), start=1):
        for i in range(n_convs):
            kw = {"input_shape": input_shape} if first else {}
            first = False
            m.add(Convolution2D(c(ch), 3, 3, border_mode="same",
                                activation="relu",
                                name=f"conv{block}_{i + 1}", **kw))
        m.add(MaxPooling2D((2, 2), name=f"pool{block}"))
    m.add(Flatten(name="flatten"))
    m.add(Dense(c(4096), activation="relu", name="fc6"))
    if has_dropout:
        m.add(Dropout(0.5, name="drop6"))
    m.add(Dense(c(4096), activation="relu", name="fc7"))
    if has_dropout:
        m.add(Dropout(0.5, name="drop7"))
    m.add(Dense(classes, activation="softmax", name="fc8"))
    return m


# ---------------------------------------------------------------------------
# SqueezeNet (reference squeezenet config; Iandola 2016 fire modules)
# ---------------------------------------------------------------------------

def _fire(x, squeeze, expand, name):
    s = Convolution2D(squeeze, 1, 1, activation="relu",
                      name=f"{name}/squeeze1x1")(x)
    e1 = Convolution2D(expand, 1, 1, activation="relu",
                       name=f"{name}/expand1x1")(s)
    e3 = Convolution2D(expand, 3, 3, border_mode="same", activation="relu",
                       name=f"{name}/expand3x3")(s)
    return _concat([e1, e3], name=f"{name}/concat")


def squeezenet(classes: int = 1000, input_shape=(224, 224, 3),
               width: float = 1.0) -> Model:
    def c(ch):
        return max(int(ch * width), 2)

    inp = Input(shape=input_shape, name="input")
    x = Convolution2D(c(64), 3, 3, subsample=(2, 2), activation="relu",
                      name="conv1")(inp)
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool1")(x)
    x = _fire(x, c(16), c(64), "fire2")
    x = _fire(x, c(16), c(64), "fire3")
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool3")(x)
    x = _fire(x, c(32), c(128), "fire4")
    x = _fire(x, c(32), c(128), "fire5")
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool5")(x)
    x = _fire(x, c(48), c(192), "fire6")
    x = _fire(x, c(48), c(192), "fire7")
    x = _fire(x, c(64), c(256), "fire8")
    x = _fire(x, c(64), c(256), "fire9")
    x = Convolution2D(classes, 1, 1, activation="relu", name="conv10")(x)
    x = GlobalAveragePooling2D(name="pool10")(x)
    out = Activation("softmax", name="prob")(x)
    return Model(inp, out, name="squeezenet")


# ---------------------------------------------------------------------------
# DenseNet (reference densenet-161 config; Huang 2017 — dense blocks with
# BN-ReLU-1x1 / BN-ReLU-3x3 composite layers and transition compression)
# ---------------------------------------------------------------------------

_DENSENET_PLANS = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24)}


def _dense_layer(x, growth, name):
    y = BatchNormalization(name=f"{name}/bn1")(x)
    y = Activation("relu", name=f"{name}/relu1")(y)
    y = Convolution2D(4 * growth, 1, 1, bias=False,
                      name=f"{name}/conv1x1")(y)
    y = BatchNormalization(name=f"{name}/bn2")(y)
    y = Activation("relu", name=f"{name}/relu2")(y)
    y = Convolution2D(growth, 3, 3, border_mode="same", bias=False,
                      name=f"{name}/conv3x3")(y)
    return _concat([x, y], name=f"{name}/concat")


def densenet(depth: int = 161, classes: int = 1000,
             input_shape=(224, 224, 3), growth_rate: int | None = None,
             block_plan=None, init_features: int | None = None) -> Model:
    if block_plan is None:
        if depth not in _DENSENET_PLANS:
            raise ValueError(
                f"densenet depth must be one of {sorted(_DENSENET_PLANS)}")
        block_plan = _DENSENET_PLANS[depth]
    growth = growth_rate or (48 if depth == 161 else 32)
    feats = init_features or 2 * growth

    inp = Input(shape=input_shape, name="input")
    x = Convolution2D(feats, 7, 7, subsample=(2, 2), border_mode="same",
                      bias=False, name="conv0")(inp)
    x = BatchNormalization(name="bn0")(x)
    x = Activation("relu", name="relu0")(x)
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="pool0")(x)
    ch = feats
    for b, n_layers in enumerate(block_plan, start=1):
        for i in range(n_layers):
            x = _dense_layer(x, growth, f"block{b}/layer{i + 1}")
            ch += growth
        if b != len(block_plan):   # transition: BN-ReLU-1x1(0.5x)-avgpool
            x = BatchNormalization(name=f"trans{b}/bn")(x)
            x = Activation("relu", name=f"trans{b}/relu")(x)
            ch = ch // 2
            x = Convolution2D(ch, 1, 1, bias=False,
                              name=f"trans{b}/conv")(x)
            x = AveragePooling2D((2, 2), name=f"trans{b}/pool")(x)
    x = BatchNormalization(name="bn_final")(x)
    x = Activation("relu", name="relu_final")(x)
    x = GlobalAveragePooling2D(name="pool_final")(x)
    out = Dense(classes, activation="softmax", name="classifier")(x)
    return Model(inp, out, name=f"densenet_{depth}")


# ---------------------------------------------------------------------------
# MobileNet v1 (reference mobilenet config; Howard 2017 — depthwise
# separable blocks with BN between the depthwise and pointwise stages,
# which is why DepthwiseConvolution2D exists as a standalone layer)
# ---------------------------------------------------------------------------

def _dw_block(x, ch, stride, name, bn_momentum=0.99):
    y = DepthwiseConvolution2D(3, 3, subsample=(stride, stride),
                               border_mode="same", bias=False,
                               name=f"{name}/dw")(x)
    y = BatchNormalization(momentum=bn_momentum, name=f"{name}/dw_bn")(y)
    y = Activation("relu", name=f"{name}/dw_relu")(y)
    y = Convolution2D(ch, 1, 1, bias=False, name=f"{name}/pw")(y)
    y = BatchNormalization(momentum=bn_momentum, name=f"{name}/pw_bn")(y)
    return Activation("relu", name=f"{name}/pw_relu")(y)


_MOBILENET_PLAN = (  # (out_channels, stride)
    (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
    (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
    (1024, 2), (1024, 1),
)


def mobilenet(classes: int = 1000, input_shape=(224, 224, 3),
              alpha: float = 1.0, has_dropout: bool = True,
              bn_momentum: float = 0.99) -> Model:
    """``bn_momentum``: running-stat averaging window; lower it for short
    training runs (a ~0.99 window needs hundreds of steps to converge
    through this many stacked BNs)."""
    def c(ch):
        return max(int(ch * alpha), 8)

    inp = Input(shape=input_shape, name="input")
    x = Convolution2D(c(32), 3, 3, subsample=(2, 2), border_mode="same",
                      bias=False, name="conv1")(inp)
    x = BatchNormalization(momentum=bn_momentum, name="conv1_bn")(x)
    x = Activation("relu", name="conv1_relu")(x)
    for i, (ch, stride) in enumerate(_MOBILENET_PLAN, start=1):
        x = _dw_block(x, c(ch), stride, f"block{i}", bn_momentum)
    x = GlobalAveragePooling2D(name="pool")(x)
    if has_dropout:
        x = Dropout(0.001, name="dropout")(x)
    out = Dense(classes, activation="softmax", name="classifier")(x)
    return Model(inp, out, name="mobilenet")


# ---------------------------------------------------------------------------
# MobileNet v2 (reference mobilenet-v2 config; Sandler 2018 — inverted
# residuals with linear bottlenecks)
# ---------------------------------------------------------------------------

_MOBILENET_V2_PLAN = (  # (expansion, out_channels, repeats, first_stride)
    (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
    (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
)


def _inverted_residual(x, in_ch, out_ch, stride, expansion, name,
                       bn_momentum=0.99):
    y = x
    hidden = in_ch * expansion
    if expansion != 1:
        y = Convolution2D(hidden, 1, 1, bias=False,
                          name=f"{name}/expand")(y)
        y = BatchNormalization(momentum=bn_momentum,
                               name=f"{name}/expand_bn")(y)
        y = Activation("relu6", name=f"{name}/expand_relu")(y)
    y = DepthwiseConvolution2D(3, 3, subsample=(stride, stride),
                               border_mode="same", bias=False,
                               name=f"{name}/dw")(y)
    y = BatchNormalization(momentum=bn_momentum, name=f"{name}/dw_bn")(y)
    y = Activation("relu6", name=f"{name}/dw_relu")(y)
    y = Convolution2D(out_ch, 1, 1, bias=False,
                      name=f"{name}/project")(y)   # linear bottleneck
    y = BatchNormalization(momentum=bn_momentum,
                           name=f"{name}/project_bn")(y)
    if stride == 1 and in_ch == out_ch:
        y = Merge(mode="sum", name=f"{name}/add")([x, y])
    return y


def mobilenet_v2(classes: int = 1000, input_shape=(224, 224, 3),
                 alpha: float = 1.0, bn_momentum: float = 0.99) -> Model:
    def c(ch):
        return max(int(ch * alpha), 8)

    inp = Input(shape=input_shape, name="input")
    x = Convolution2D(c(32), 3, 3, subsample=(2, 2), border_mode="same",
                      bias=False, name="conv1")(inp)
    x = BatchNormalization(momentum=bn_momentum, name="conv1_bn")(x)
    x = Activation("relu6", name="conv1_relu")(x)
    in_ch = c(32)
    for b, (t, ch, n, s) in enumerate(_MOBILENET_V2_PLAN, start=1):
        for i in range(n):
            stride = s if i == 0 else 1
            x = _inverted_residual(x, in_ch, c(ch), stride, t,
                                   f"block{b}_{i + 1}", bn_momentum)
            in_ch = c(ch)
    # canonical v2 rule: the last conv stays at 1280 unless alpha > 1
    last = c(1280) if alpha > 1.0 else 1280
    x = Convolution2D(last, 1, 1, bias=False, name="conv_last")(x)
    x = BatchNormalization(momentum=bn_momentum, name="conv_last_bn")(x)
    x = Activation("relu6", name="conv_last_relu")(x)
    x = GlobalAveragePooling2D(name="pool")(x)
    out = Dense(classes, activation="softmax", name="classifier")(x)
    return Model(inp, out, name="mobilenet_v2")
