"""Caffe model loader: prototxt + caffemodel → a jit-compiled zoo layer.

Reference: models/caffe/CaffeLoader.scala:63-671 (+ LayerConverter /
V1LayerConverter) — converts caffe NetParameter protos into a BigDL graph
with copied weights.

TPU re-design: like the ONNX loader, the network is interpreted once at
trace time into a single XLA program (:class:`CaffeNet`), keeping caffe's
NCHW layout (XLA re-lays out internally).  The prototxt is parsed with a
small protobuf *text-format* parser and the caffemodel with the generic
wire-format reader shared with :mod:`..pipeline.api.onnx.proto` — no caffe
or protobuf runtime required.  Field numbers follow the public caffe.proto
(frozen by protobuf compatibility rules).
"""

from __future__ import annotations

import re

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer
from analytics_zoo_tpu.pipeline.api.onnx.proto import (
    _iter_fields,
    _read_varint,
)


# ---------------------------------------------------------------------------
# prototxt (protobuf text format)
# ---------------------------------------------------------------------------

_TOKEN = re.compile(
    r"""[A-Za-z_][A-Za-z0-9_]*|"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*'"""
    r"""|[-+]?[0-9.][0-9.eE+-]*|[{}:]""",
)


def _tokenize(text):
    # strip comments
    text = re.sub(r"#[^\n]*", "", text)
    return _TOKEN.findall(text)


def _parse_value(tok):
    if tok and tok[0] in "\"'":
        return tok[1:-1]
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        return tok  # enum identifier / bool


def _parse_message(tokens, pos):
    """Parse `field: value` / `field { ... }` pairs until '}' or EOF.
    Repeated fields accumulate into lists."""
    msg: dict = {}
    n = len(tokens)
    while pos < n and tokens[pos] != "}":
        key = tokens[pos]
        pos += 1
        if pos < n and tokens[pos] == ":":
            pos += 1
            val = _parse_value(tokens[pos])
            pos += 1
        elif pos < n and tokens[pos] == "{":
            val, pos = _parse_message(tokens, pos + 1)
            assert tokens[pos] == "}", "unbalanced braces in prototxt"
            pos += 1
        else:
            raise ValueError(f"prototxt parse error near {key!r}")
        if key in msg:
            if not isinstance(msg[key], list):
                msg[key] = [msg[key]]
            msg[key].append(val)
        else:
            msg[key] = val
    return msg, pos


def parse_prototxt(text: str) -> dict:
    tokens = _tokenize(text)
    msg, pos = _parse_message(tokens, 0)
    return msg


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


# ---------------------------------------------------------------------------
# V1 (upgrade_proto-era) layer normalization — reference V1LayerConverter
# ---------------------------------------------------------------------------

# V1LayerParameter.LayerType enum (public caffe.proto, frozen): both the
# text-format enum identifiers and the binary enum ints map to the modern
# string type names the converters use (reference V1LayerConverter.scala:39
# implements the same legacy set; loss/data types map to the names the
# train-only-layer filter already drops).
_V1_LAYER_TYPES = {
    "NONE": (0, None),
    "ACCURACY": (1, "Accuracy"),
    "BNLL": (2, "BNLL"),
    "CONCAT": (3, "Concat"),
    "CONVOLUTION": (4, "Convolution"),
    "DATA": (5, "Data"),
    "DROPOUT": (6, "Dropout"),
    "EUCLIDEAN_LOSS": (7, "EuclideanLoss"),
    "FLATTEN": (8, "Flatten"),
    "HDF5_DATA": (9, "HDF5Data"),
    "HDF5_OUTPUT": (10, "HDF5Output"),
    "IM2COL": (11, "Im2col"),
    "IMAGE_DATA": (12, "ImageData"),
    "INFOGAIN_LOSS": (13, "InfogainLoss"),
    "INNER_PRODUCT": (14, "InnerProduct"),
    "LRN": (15, "LRN"),
    "MULTINOMIAL_LOGISTIC_LOSS": (16, "MultinomialLogisticLoss"),
    "POOLING": (17, "Pooling"),
    "RELU": (18, "ReLU"),
    "SIGMOID": (19, "Sigmoid"),
    "SOFTMAX": (20, "Softmax"),
    "SOFTMAX_LOSS": (21, "SoftmaxWithLoss"),
    "SPLIT": (22, "Split"),
    "TANH": (23, "TanH"),
    "WINDOW_DATA": (24, "WindowData"),
    "ELTWISE": (25, "Eltwise"),
    "POWER": (26, "Power"),
    "SIGMOID_CROSS_ENTROPY_LOSS": (27, "SigmoidCrossEntropyLoss"),
    "HINGE_LOSS": (28, "HingeLoss"),
    "MEMORY_DATA": (29, "MemoryData"),
    "ARGMAX": (30, "ArgMax"),
    "THRESHOLD": (31, "Threshold"),
    "DUMMY_DATA": (32, "DummyData"),
    "SLICE": (33, "Slice"),
    "MVN": (34, "MVN"),
    "ABSVAL": (35, "AbsVal"),
    "SILENCE": (36, "Silence"),
    "CONTRASTIVE_LOSS": (37, "ContrastiveLoss"),
    "EXP": (38, "Exp"),
    "DECONVOLUTION": (39, "Deconvolution"),
}
_V1_BY_NAME = {k: v[1] for k, v in _V1_LAYER_TYPES.items()}
_V1_BY_INT = {v[0]: v[1] for v in _V1_LAYER_TYPES.values()}


def normalize_v1_layer(ly: dict) -> dict:
    """Translate an upgrade_proto-era ``layers { type: CONVOLUTION }``
    entry (enum type — text identifier or binary int) into the modern
    string-typed form the converters consume.  Modern entries pass through
    untouched.  Reference: CaffeLoader.scala:63-75 selecting
    V1LayerConverter for V1 nets."""
    t = ly.get("type")
    new_t = None
    if isinstance(t, int):
        new_t = _V1_BY_INT.get(t)
        if new_t is None:
            raise NotImplementedError(f"unknown V1 layer type enum {t}")
    elif isinstance(t, str) and t in _V1_BY_NAME:
        new_t = _V1_BY_NAME[t]
    if new_t is None:
        return ly
    out = dict(ly)
    out["type"] = new_t
    return out


# ---------------------------------------------------------------------------
# caffemodel (binary NetParameter) — only blobs are needed; topology comes
# from the prototxt
# ---------------------------------------------------------------------------

def _decode_blob(buf) -> np.ndarray:
    import struct

    dims, data, legacy = [], [], {}
    for fnum, wtype, val in _iter_fields(buf):
        if fnum == 7:  # shape: BlobShape{ dim=1 }
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1:
                    if w2 == 2:  # packed
                        pos = 0
                        while pos < len(v2):
                            d, pos = _read_varint(v2, pos)
                            dims.append(d)
                    else:
                        dims.append(v2)
        elif fnum == 5:  # data: repeated float (packed)
            if wtype == 2:
                data.append(np.frombuffer(val, dtype=np.float32))
            else:
                data.append(np.asarray(
                    [struct.unpack("<f", struct.pack("<i", val))[0]],
                    dtype=np.float32,
                ))
        elif fnum in (1, 2, 3, 4):  # legacy num/channels/height/width
            legacy[fnum] = val
    arr = (np.concatenate(data) if data
           else np.zeros(0, dtype=np.float32))
    legacy_format = not dims and bool(legacy)
    if legacy_format:
        dims = [legacy.get(i, 1) for i in (1, 2, 3, 4)]
    if dims and int(np.prod(dims)) == arr.size:
        arr = arr.reshape(dims)
    # squeeze ONLY the legacy num/channels/height/width (1,1,H,W) padding
    # on FC/bias blobs — a modern 4D blob with num_output=1 (shape
    # (1,C,kh,kw) via the `shape` field) must stay 4D
    if legacy_format and arr.ndim == 4 and arr.shape[0] == 1 \
            and arr.shape[1] == 1:
        arr = arr[0, 0]
    return arr


def parse_caffemodel(data: bytes) -> dict:
    """name -> [blob arrays] for every layer carrying weights.  Handles both
    `layer` (field 100, LayerParameter: name=1, blobs=7) and legacy
    `layers` (field 2, V1LayerParameter: name=4, blobs=6) messages
    (CaffeLoader supports both via LayerConverter/V1LayerConverter)."""
    out: dict = {}
    for fnum, _, val in _iter_fields(memoryview(data)):
        if fnum not in (100, 2):
            continue
        name_field = 1 if fnum == 100 else 4
        blob_field = 7 if fnum == 100 else 6
        name, blobs = "", []
        for f2, _, v2 in _iter_fields(val):
            if f2 == name_field and isinstance(v2, bytes):
                name = v2.decode("utf-8", "replace")
            elif f2 == blob_field:
                blobs.append(_decode_blob(v2))
        if name and blobs:
            out[name] = blobs
    return out


# ---------------------------------------------------------------------------
# layer execution
# ---------------------------------------------------------------------------

def _ntup(param, base, h_key, w_key, default):
    """caffe's kernel/stride/pad trio: either repeated `base` or explicit
    `_h`/`_w` values."""
    h = param.get(h_key)
    w = param.get(w_key)
    if h is not None or w is not None:
        return (int(h or default), int(w or default))
    v = _as_list(param.get(base))
    if not v:
        return (default, default)
    if len(v) == 1:
        return (int(v[0]), int(v[0]))
    return (int(v[0]), int(v[1]))


class CaffeNet(Layer):
    """A caffe network as a zoo Layer (reference CaffeLoader.scala).

    Supported layer types mirror the reference's converter set:
    Input/Data, Convolution, InnerProduct, Pooling (MAX/AVE, caffe ceil
    rounding), ReLU, PReLU, Sigmoid, TanH, ELU, AbsVal, Power, Exp, Log,
    LRN (across-channels), BatchNorm, Scale, Bias, Concat, Eltwise,
    Softmax, Dropout (identity at inference), Flatten, Reshape, Split.
    Weights loaded from the caffemodel become trainable params.
    """

    def __init__(self, net_def: dict, blobs: dict | None = None,
                 trainable=True, name=None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.net_def = net_def
        self.trainable = trainable
        raw_layers = _as_list(net_def.get("layer")) \
            or _as_list(net_def.get("layers"))
        # V1 (upgrade_proto-era) nets carry enum layer types — normalize
        # them to the modern string names first (V1LayerConverter role)
        raw_layers = [normalize_v1_layer(ly) for ly in raw_layers]
        # drop train-only layers (phase TRAIN, loss/accuracy heads)
        self.layers = []
        for ly in raw_layers:
            t = str(ly.get("type", ""))
            include = ly.get("include", {})
            phase = include.get("phase") if isinstance(include, dict) \
                else None
            if phase == "TRAIN" or t in (
                "SoftmaxWithLoss", "Accuracy", "EuclideanLoss",
                "SigmoidCrossEntropyLoss", "HingeLoss", "Data",
                "ImageData", "HDF5Data",
                # V1-era train/data heads (V1LayerConverter drop set)
                "WindowData", "MemoryData", "DummyData", "HDF5Output",
                "MultinomialLogisticLoss", "InfogainLoss",
                "ContrastiveLoss", "Silence",
            ):
                continue
            self.layers.append(ly)
        self._blobs = blobs or {}
        self._handler_check()

        # network inputs: explicit `input:` fields or Input layers
        self.input_names = [str(v) for v in _as_list(net_def.get("input"))]
        self._input_shapes = {}
        shapes = _as_list(net_def.get("input_shape"))
        for iname, shp in zip(self.input_names, shapes):
            self._input_shapes[iname] = tuple(
                int(d) for d in _as_list(shp.get("dim"))
            )
        for ly in self.layers:
            if str(ly.get("type")) == "Input":
                top = str(ly["top"])
                self.input_names.append(top)
                shp = ly.get("input_param", {}).get("shape", {})
                if shp:
                    self._input_shapes[top] = tuple(
                        int(d) for d in _as_list(shp.get("dim"))
                    )
        if not self.input_names:
            raise ValueError("caffe net has no inputs (input: or Input)")
        if len(self.input_names) == 1:
            shp = self._input_shapes.get(self.input_names[0])
            if shp and self._input_shape is None:
                self._input_shape = tuple(shp[1:])

        # caffe has no explicit outputs; the conventional outputs are the
        # tops never consumed as bottoms (fixed by net_def — precompute)
        consumed, produced = set(), []
        for ly in self.layers:
            consumed.update(str(b) for b in _as_list(ly.get("bottom")))
            for top in _as_list(ly.get("top")):
                produced.append(str(top))
        self.output_names = [t for t in dict.fromkeys(produced)
                             if t not in consumed] \
            or produced[-1:]

    _HANDLED = {
        "Input", "Convolution", "InnerProduct", "Pooling", "ReLU",
        "PReLU", "Sigmoid", "TanH", "ELU", "AbsVal", "Power", "Exp",
        "Log", "LRN", "BatchNorm", "Scale", "Bias", "Concat", "Eltwise",
        "Softmax", "Dropout", "Flatten", "Reshape", "Split",
    }

    def _handler_check(self):
        missing = sorted({
            str(ly.get("type")) for ly in self.layers
            if str(ly.get("type")) not in self._HANDLED
        })
        if missing:
            raise NotImplementedError(
                f"caffe layer types without converters: {missing} "
                f"(supported: {sorted(self._HANDLED)})"
            )

    # -- weights -----------------------------------------------------------
    def build(self, input_shape):
        from analytics_zoo_tpu.pipeline.api.onnx import _Fixed

        for ly in self.layers:
            lname = str(ly.get("name", ""))
            for bi, arr in enumerate(self._blobs.get(lname, [])):
                self.add_weight(f"{lname}/blob{bi}", arr.shape,
                                _Fixed(arr), trainable=self.trainable)

    def _w(self, weights, ly, idx, default=None, required=False):
        lname = str(ly.get("name", ""))
        key = f"{lname}/blob{idx}"
        if key in weights:
            return weights[key]
        if required:
            # Round-1 advisor finding: without this, load_caffe with no
            # .caffemodel crashed deep inside lax with None weights.
            raise ValueError(
                f"caffe layer {lname!r} ({ly.get('type')}) has no blob "
                f"{idx}: pass model_path=<.caffemodel> to load_caffe (the "
                "prototxt alone carries no weights)")
        return default

    # -- forward -----------------------------------------------------------
    def call(self, params, inputs, state=None, training=False, rng=None):
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        env = dict(zip(self.input_names, xs))
        weights = params if self.trainable else (state or {})

        for ly in self.layers:
            t = str(ly.get("type"))
            if t == "Input":
                continue
            bottoms = [env[str(b)] for b in _as_list(ly.get("bottom"))]
            tops = [str(v) for v in _as_list(ly.get("top"))]
            out = self._apply_layer(t, ly, bottoms, weights)
            if t == "Split":
                for top in tops:
                    env[top] = out
            else:
                env[tops[0]] = out

        result = [env[o] for o in self.output_names if o in env]
        result = result if len(result) > 1 else result[0]
        if self.stateful:
            return result, state
        return result

    def _apply_layer(self, t, ly, bottoms, weights):
        x = bottoms[0] if bottoms else None
        if t == "Convolution":
            p = ly.get("convolution_param", {})
            k = _ntup(p, "kernel_size", "kernel_h", "kernel_w", 1)
            s = _ntup(p, "stride", "stride_h", "stride_w", 1)
            pad = _ntup(p, "pad", "pad_h", "pad_w", 0)
            dil = int(_as_list(p.get("dilation"))[0]) \
                if p.get("dilation") is not None else 1
            group = int(p.get("group", 1))
            w = self._w(weights, ly, 0, required=True)
            y = lax.conv_general_dilated(
                x, w, window_strides=s,
                padding=[(pad[0], pad[0]), (pad[1], pad[1])],
                rhs_dilation=(dil, dil),
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=group,
            )
            b = self._w(weights, ly, 1)
            if b is not None and p.get("bias_term", True) is not False:
                y = y + b.reshape(1, -1, 1, 1)
            return y
        if t == "InnerProduct":
            p = ly.get("inner_product_param", {})
            w = self._w(weights, ly, 0, required=True)  # (out, in)
            xf = x.reshape(x.shape[0], -1)
            y = xf @ w.T
            b = self._w(weights, ly, 1)
            if b is not None and p.get("bias_term", True) is not False:
                y = y + b
            return y
        if t == "Pooling":
            p = ly.get("pooling_param", {})
            if p.get("global_pooling") in (True, "true", 1):
                op = p.get("pool", "MAX")
                fn = jnp.max if op in ("MAX", 0) else jnp.mean
                return fn(x, axis=(2, 3), keepdims=True)
            k = _ntup(p, "kernel_size", "kernel_h", "kernel_w", 1)
            s = _ntup(p, "stride", "stride_h", "stride_w", 1)
            pad = _ntup(p, "pad", "pad_h", "pad_w", 0)
            # caffe rounds pooling output UP, then drops a window that
            # would start entirely inside the padding
            n_out, extra = [], []
            for size, ki, st, pd in zip(x.shape[2:], k, s, pad):
                n = -(-(size + 2 * pd - ki) // st) + 1
                if pd and (n - 1) * st >= size + pd:
                    n -= 1
                n_out.append(n)
                extra.append(max(0, (n - 1) * st + ki - (size + 2 * pd)))
            window = (1, 1) + k
            strides = (1, 1) + s
            if p.get("pool", "MAX") in ("STOCHASTIC", 2):
                # Round-1 advisor finding: executing STOCHASTIC as AVE is
                # silently wrong; caffe stochastic pooling has no
                # deterministic inference equivalent here.
                raise NotImplementedError(
                    f"caffe STOCHASTIC pooling (layer "
                    f"{ly.get('name')!r}) is not supported")
            if p.get("pool", "MAX") in ("MAX", 0):
                # -inf padding: padded cells never win the max (caffe
                # clips MAX windows to the real image)
                full = [(0, 0), (0, 0)] + [
                    (pd, pd + ex) for pd, ex in zip(pad, extra)
                ]
                return lax.reduce_window(x, -jnp.inf, lax.max, window,
                                         strides, full)
            # AVE: caffe sums real cells but divides by the window extent
            # clipped to the padded canvas [−pad, size+pad) — pad cells
            # count in the denominator, the ceil extension does not.
            xp = jnp.pad(x, [(0, 0), (0, 0)] + [(pd, pd) for pd in pad])
            full = [(0, 0), (0, 0)] + [(0, ex) for ex in extra]
            y = lax.reduce_window(xp, 0.0, lax.add, window, strides, full)
            cnt = lax.reduce_window(jnp.ones_like(xp), 0.0, lax.add,
                                    window, strides, full)
            return y / cnt
        if t == "ReLU":
            slope = ly.get("relu_param", {}).get("negative_slope", 0.0)
            if slope:
                return jnp.where(x >= 0, x, slope * x)
            return jax.nn.relu(x)
        if t == "PReLU":
            a = self._w(weights, ly, 0, required=True)
            return jnp.where(x >= 0, x, a.reshape(1, -1, 1, 1) * x)
        if t == "Sigmoid":
            return jax.nn.sigmoid(x)
        if t == "TanH":
            return jnp.tanh(x)
        if t == "ELU":
            alpha = ly.get("elu_param", {}).get("alpha", 1.0)
            return jnp.where(x >= 0, x, alpha * jnp.expm1(x))
        if t == "AbsVal":
            return jnp.abs(x)
        if t == "Power":
            p = ly.get("power_param", {})
            return jnp.power(
                p.get("shift", 0.0) + p.get("scale", 1.0) * x,
                p.get("power", 1.0),
            )
        if t == "Exp":
            p = ly.get("exp_param", {})
            base = p.get("base", -1.0)
            y = p.get("scale", 1.0) * x + p.get("shift", 0.0)
            return jnp.exp(y) if base == -1.0 else jnp.power(base, y)
        if t == "Log":
            p = ly.get("log_param", {})
            base = p.get("base", -1.0)
            y = p.get("scale", 1.0) * x + p.get("shift", 0.0)
            out = jnp.log(y)
            return out if base == -1.0 else out / np.log(base)
        if t == "LRN":
            p = ly.get("lrn_param", {})
            size = int(p.get("local_size", 5))
            alpha = p.get("alpha", 1.0)
            beta = p.get("beta", 0.75)
            kk = p.get("k", 1.0)
            lo = (size - 1) // 2
            sq = jnp.square(x)
            region = p.get("norm_region", "ACROSS_CHANNELS")
            if region in ("WITHIN_CHANNEL", 1):
                # caffe WITHIN_CHANNEL: spatial size x size window per
                # channel, denominator normalized by the window AREA
                # (round-1 advisor finding: norm_region was ignored).
                win = lax.reduce_window(
                    sq, 0.0, lax.add, (1, 1, size, size), (1, 1, 1, 1),
                    [(0, 0), (0, 0), (lo, size - 1 - lo),
                     (lo, size - 1 - lo)],
                )
                return x / jnp.power(kk + alpha / (size * size) * win, beta)
            win = lax.reduce_window(
                sq, 0.0, lax.add, (1, size, 1, 1), (1, 1, 1, 1),
                [(0, 0), (lo, size - 1 - lo), (0, 0), (0, 0)],
            )
            return x / jnp.power(kk + alpha / size * win, beta)
        if t == "BatchNorm":
            p = ly.get("batch_norm_param", {})
            eps = p.get("eps", 1e-5)
            mean = self._w(weights, ly, 0, required=True)
            var = self._w(weights, ly, 1, required=True)
            factor = self._w(weights, ly, 2)
            if factor is not None:
                f = factor.reshape(())
                scale = jnp.where(f == 0, 0.0, 1.0 / f)
                mean = mean * scale
                var = var * scale
            shape = (1, -1, 1, 1)
            return (x - mean.reshape(shape)) \
                * lax.rsqrt(var.reshape(shape) + eps)
        if t == "Scale":
            p = ly.get("scale_param", {})
            gamma = self._w(weights, ly, 0, required=True)
            # per-channel affine over axis 1, broadcast over trailing dims
            shape = (1, -1) + (1,) * (x.ndim - 2)
            y = x * gamma.reshape(shape)
            beta = self._w(weights, ly, 1)
            if beta is not None and p.get("bias_term", False) \
                    is not False:
                y = y + beta.reshape(shape)
            return y
        if t == "Bias":
            b = self._w(weights, ly, 0)
            return x + (b.reshape(1, -1, 1, 1) if x.ndim == 4 else b)
        if t == "Concat":
            p = ly.get("concat_param", {})
            axis = int(p.get("axis", p.get("concat_dim", 1)))
            return jnp.concatenate(bottoms, axis=axis)
        if t == "Eltwise":
            p = ly.get("eltwise_param", {})
            op = p.get("operation", "SUM")
            if op in ("PROD", 0):
                out = bottoms[0]
                for b in bottoms[1:]:
                    out = out * b
                return out
            if op in ("MAX", 2):
                out = bottoms[0]
                for b in bottoms[1:]:
                    out = jnp.maximum(out, b)
                return out
            coeff = [float(c) for c in _as_list(p.get("coeff"))] \
                or [1.0] * len(bottoms)
            out = coeff[0] * bottoms[0]
            for c, b in zip(coeff[1:], bottoms[1:]):
                out = out + c * b
            return out
        if t == "Softmax":
            axis = int(ly.get("softmax_param", {}).get("axis", 1))
            return jax.nn.softmax(x, axis=axis)
        if t == "Dropout":
            return x  # inference: identity (reference drops these too)
        if t == "Flatten":
            return x.reshape(x.shape[0], -1)
        if t == "Reshape":
            shp = ly.get("reshape_param", {}).get("shape", {})
            dims = [int(d) for d in _as_list(shp.get("dim"))]
            out = [x.shape[i] if d == 0 else d
                   for i, d in enumerate(dims)]
            return jnp.reshape(x, out)
        if t == "Split":
            return x
        raise NotImplementedError(t)  # pragma: no cover

    @property
    def stateful(self):
        return not self.trainable

    def init_state(self):
        if self.trainable:
            return super().init_state()
        state = {}
        for ly in self.layers:
            lname = str(ly.get("name", ""))
            for bi, arr in enumerate(self._blobs.get(lname, [])):
                state[f"{lname}/blob{bi}"] = jnp.asarray(arr)
        return state


def load_caffe(def_path, model_path=None, trainable=True) -> CaffeNet:
    """Reference ``Net.loadCaffe(defPath, modelPath)`` →
    CaffeLoader.loadCaffe (CaffeLoader.scala:63)."""
    with open(def_path, "r", encoding="utf-8") as f:
        net_def = parse_prototxt(f.read())
    blobs = {}
    if model_path is not None:
        with open(model_path, "rb") as f:
            blobs = parse_caffemodel(f.read())
    return CaffeNet(net_def, blobs, trainable=trainable)
