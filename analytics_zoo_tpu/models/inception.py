"""Inception-v1 (GoogLeNet) — the reference's flagship ImageNet training
example (zoo/.../examples/inception/Train.scala:31-120 trains BigDL's
Inception_v1_NoAuxClassifier; python twin
pyzoo/zoo/examples/inception/inception.py:119-165).

NHWC graph built on the Model API: every inception block is four parallel
towers merged on the channel axis — all MXU convolutions in one XLA
program.  LRN layers match the reference's SpatialCrossMapLRN(5, 1e-4,
0.75) placement.
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    LRN2D,
    AveragePooling2D,
    Convolution2D,
    Dense,
    Dropout,
    Flatten,
    MaxPooling2D,
    Merge,
)

# (1x1, [3x3_reduce, 3x3], [5x5_reduce, 5x5], pool_proj) per block —
# inception.py:137-157 configs
_V1_BLOCKS = {
    "3a": (64, (96, 128), (16, 32), 32),
    "3b": (128, (128, 192), (32, 96), 64),
    "4a": (192, (96, 208), (16, 48), 64),
    "4b": (160, (112, 224), (24, 64), 64),
    "4c": (128, (128, 256), (24, 64), 64),
    "4d": (112, (144, 288), (32, 64), 64),
    "4e": (256, (160, 320), (32, 128), 128),
    "5a": (256, (160, 320), (32, 128), 128),
    "5b": (384, (192, 384), (48, 128), 128),
}


def _conv(x, filters, k, stride=1, name=None):
    return Convolution2D(filters, k, k, subsample=(stride, stride),
                         border_mode="same", activation="relu",
                         init="glorot_uniform", name=name)(x)


def _inception_block(x, key: str):
    """inception_layer_v1 (inception.py:83-117): 1x1 | 1x1->3x3 |
    1x1->5x5 | maxpool->1x1, channel-concat."""
    c1, (c3r, c3), (c5r, c5), cp = _V1_BLOCKS[key]
    p = f"inception_{key}/"
    t1 = _conv(x, c1, 1, name=p + "1x1")
    t2 = _conv(_conv(x, c3r, 1, name=p + "3x3_reduce"), c3, 3,
               name=p + "3x3")
    t3 = _conv(_conv(x, c5r, 1, name=p + "5x5_reduce"), c5, 5,
               name=p + "5x5")
    t4 = MaxPooling2D(pool_size=(3, 3), strides=(1, 1),
                      border_mode="same", name=p + "pool")(x)
    t4 = _conv(t4, cp, 1, name=p + "pool_proj")
    return Merge(mode="concat", concat_axis=-1, name=p + "output")(
        [t1, t2, t3, t4])


class Inception:
    """Factory namespace, like the reference companion objects."""

    @staticmethod
    def v1(classes: int = 1000, input_shape=(224, 224, 3),
           has_dropout: bool = True) -> Model:
        """Inception_v1_NoAuxClassifier
        (inception.py:119-165 layer-for-layer)."""
        inp = Input(shape=input_shape, name="input")
        x = _conv(inp, 64, 7, stride=2, name="conv1/7x7_s2")
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                         border_mode="same", name="pool1/3x3_s2")(x)
        x = LRN2D(alpha=1e-4, k=1.0, beta=0.75, n=5,
                  name="pool1/norm1")(x)
        x = _conv(x, 64, 1, name="conv2/3x3_reduce")
        x = _conv(x, 192, 3, name="conv2/3x3")
        x = LRN2D(alpha=1e-4, k=1.0, beta=0.75, n=5, name="conv2/norm2")(x)
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                         border_mode="same", name="pool2/3x3_s2")(x)
        x = _inception_block(x, "3a")
        x = _inception_block(x, "3b")
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                         border_mode="same", name="pool3/3x3_s2")(x)
        for key in ("4a", "4b", "4c", "4d", "4e"):
            x = _inception_block(x, key)
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                         border_mode="same", name="pool4/3x3_s2")(x)
        x = _inception_block(x, "5a")
        x = _inception_block(x, "5b")
        pool = input_shape[0] // 32
        x = AveragePooling2D(pool_size=(pool, pool), strides=(1, 1),
                             name="pool5")(x)
        x = Flatten()(x)
        if has_dropout:
            x = Dropout(0.4, name="pool5/drop")(x)
        out = Dense(classes, activation="softmax",
                    name="loss3/classifier")(x)
        return Model(inp, out, name="inception_v1")


# ---------------------------------------------------------------------------
# Inception-v3 (reference inception-v3 config,
# ImageClassificationConfig.scala:35-36; Szegedy 2015 "Rethinking the
# Inception Architecture" — factorized 7x7 and asymmetric 1xN/Nx1 convs,
# BN after every conv)
# ---------------------------------------------------------------------------

from analytics_zoo_tpu.pipeline.api.keras.layers import (  # noqa: E402
    Activation,
    BatchNormalization,
    GlobalAveragePooling2D,
)


def _cbn(x, filters, kr, kc=None, stride=1, mode="same", name=None,
         bn_momentum=0.99):
    """conv (no bias) + BN + relu — the v3 building unit."""
    kc = kc if kc is not None else kr
    y = Convolution2D(filters, kr, kc, subsample=(stride, stride),
                      border_mode=mode, bias=False, name=f"{name}/conv")(x)
    y = BatchNormalization(momentum=bn_momentum, name=f"{name}/bn")(y)
    return Activation("relu", name=f"{name}/relu")(y)


def _v3_pool_proj(x, ch, name, bn_momentum):
    p = AveragePooling2D(pool_size=(3, 3), strides=(1, 1),
                         border_mode="same", name=f"{name}/pool")(x)
    return _cbn(p, ch, 1, name=f"{name}/pool_proj",
                bn_momentum=bn_momentum)


def _v3_block_a(x, c, pool_ch, name, m):
    """35x35 module: 1x1 | 5x5 | double-3x3 | pool-proj."""
    b1 = _cbn(x, c(64), 1, name=f"{name}/1x1", bn_momentum=m)
    b5 = _cbn(x, c(48), 1, name=f"{name}/5x5_reduce", bn_momentum=m)
    b5 = _cbn(b5, c(64), 5, name=f"{name}/5x5", bn_momentum=m)
    b3 = _cbn(x, c(64), 1, name=f"{name}/3x3dbl_reduce", bn_momentum=m)
    b3 = _cbn(b3, c(96), 3, name=f"{name}/3x3dbl_1", bn_momentum=m)
    b3 = _cbn(b3, c(96), 3, name=f"{name}/3x3dbl_2", bn_momentum=m)
    bp = _v3_pool_proj(x, c(pool_ch), name, m)
    return Merge(mode="concat", concat_axis=-1, name=f"{name}/concat")(
        [b1, b5, b3, bp])


def _v3_reduction_a(x, c, name, m):
    b3 = _cbn(x, c(384), 3, stride=2, mode="valid",
              name=f"{name}/3x3", bn_momentum=m)
    bd = _cbn(x, c(64), 1, name=f"{name}/3x3dbl_reduce", bn_momentum=m)
    bd = _cbn(bd, c(96), 3, name=f"{name}/3x3dbl_1", bn_momentum=m)
    bd = _cbn(bd, c(96), 3, stride=2, mode="valid",
              name=f"{name}/3x3dbl_2", bn_momentum=m)
    bp = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                      name=f"{name}/pool")(x)
    return Merge(mode="concat", concat_axis=-1, name=f"{name}/concat")(
        [b3, bd, bp])


def _v3_block_b(x, c, mid, name, m):
    """17x17 module: 1x1 | 1x7-7x1 | double 7x7 | pool-proj (factorized
    asymmetric convolutions — the paper's signature)."""
    b1 = _cbn(x, c(192), 1, name=f"{name}/1x1", bn_momentum=m)
    b7 = _cbn(x, c(mid), 1, name=f"{name}/7x7_reduce", bn_momentum=m)
    b7 = _cbn(b7, c(mid), 1, 7, name=f"{name}/7x7_1x7", bn_momentum=m)
    b7 = _cbn(b7, c(192), 7, 1, name=f"{name}/7x7_7x1", bn_momentum=m)
    bd = _cbn(x, c(mid), 1, name=f"{name}/7x7dbl_reduce", bn_momentum=m)
    bd = _cbn(bd, c(mid), 7, 1, name=f"{name}/7x7dbl_1", bn_momentum=m)
    bd = _cbn(bd, c(mid), 1, 7, name=f"{name}/7x7dbl_2", bn_momentum=m)
    bd = _cbn(bd, c(mid), 7, 1, name=f"{name}/7x7dbl_3", bn_momentum=m)
    bd = _cbn(bd, c(192), 1, 7, name=f"{name}/7x7dbl_4", bn_momentum=m)
    bp = _v3_pool_proj(x, c(192), name, m)
    return Merge(mode="concat", concat_axis=-1, name=f"{name}/concat")(
        [b1, b7, bd, bp])


def _v3_reduction_b(x, c, name, m):
    b3 = _cbn(x, c(192), 1, name=f"{name}/3x3_reduce", bn_momentum=m)
    b3 = _cbn(b3, c(320), 3, stride=2, mode="valid",
              name=f"{name}/3x3", bn_momentum=m)
    b7 = _cbn(x, c(192), 1, name=f"{name}/7x7_reduce", bn_momentum=m)
    b7 = _cbn(b7, c(192), 1, 7, name=f"{name}/7x7_1x7", bn_momentum=m)
    b7 = _cbn(b7, c(192), 7, 1, name=f"{name}/7x7_7x1", bn_momentum=m)
    b7 = _cbn(b7, c(192), 3, stride=2, mode="valid",
              name=f"{name}/7x7_3x3", bn_momentum=m)
    bp = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                      name=f"{name}/pool")(x)
    return Merge(mode="concat", concat_axis=-1, name=f"{name}/concat")(
        [b3, b7, bp])


def _v3_block_c(x, c, name, m):
    """8x8 module: 1x1 | 3x3-split(1x3 + 3x1) | dbl-3x3-split | pool."""
    b1 = _cbn(x, c(320), 1, name=f"{name}/1x1", bn_momentum=m)
    b3 = _cbn(x, c(384), 1, name=f"{name}/3x3_reduce", bn_momentum=m)
    b3a = _cbn(b3, c(384), 1, 3, name=f"{name}/3x3_1x3", bn_momentum=m)
    b3b = _cbn(b3, c(384), 3, 1, name=f"{name}/3x3_3x1", bn_momentum=m)
    bd = _cbn(x, c(448), 1, name=f"{name}/dbl_reduce", bn_momentum=m)
    bd = _cbn(bd, c(384), 3, name=f"{name}/dbl_3x3", bn_momentum=m)
    bda = _cbn(bd, c(384), 1, 3, name=f"{name}/dbl_1x3", bn_momentum=m)
    bdb = _cbn(bd, c(384), 3, 1, name=f"{name}/dbl_3x1", bn_momentum=m)
    bp = _v3_pool_proj(x, c(192), name, m)
    return Merge(mode="concat", concat_axis=-1, name=f"{name}/concat")(
        [b1, b3a, b3b, bda, bdb, bp])


def inception_v3(classes: int = 1000, input_shape=(299, 299, 3),
                 width: float = 1.0, has_dropout: bool = True,
                 bn_momentum: float = 0.99) -> Model:
    """Inception-v3 (299x299 canonical; any input >= ~75px works).
    ``width`` scales every tower's filter count for toy-scale CI."""
    def c(ch):
        return max(int(ch * width), 4)

    m = bn_momentum
    inp = Input(shape=input_shape, name="input")
    x = _cbn(inp, c(32), 3, stride=2, mode="valid", name="stem/conv1",
             bn_momentum=m)
    x = _cbn(x, c(32), 3, mode="valid", name="stem/conv2", bn_momentum=m)
    x = _cbn(x, c(64), 3, name="stem/conv3", bn_momentum=m)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                     name="stem/pool1")(x)
    x = _cbn(x, c(80), 1, mode="valid", name="stem/conv4", bn_momentum=m)
    x = _cbn(x, c(192), 3, mode="valid", name="stem/conv5", bn_momentum=m)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                     name="stem/pool2")(x)
    x = _v3_block_a(x, c, 32, "mixed_5b", m)
    x = _v3_block_a(x, c, 64, "mixed_5c", m)
    x = _v3_block_a(x, c, 64, "mixed_5d", m)
    x = _v3_reduction_a(x, c, "mixed_6a", m)
    x = _v3_block_b(x, c, 128, "mixed_6b", m)
    x = _v3_block_b(x, c, 160, "mixed_6c", m)
    x = _v3_block_b(x, c, 160, "mixed_6d", m)
    x = _v3_block_b(x, c, 192, "mixed_6e", m)
    x = _v3_reduction_b(x, c, "mixed_7a", m)
    x = _v3_block_c(x, c, "mixed_7b", m)
    x = _v3_block_c(x, c, "mixed_7c", m)
    x = GlobalAveragePooling2D(name="pool")(x)
    if has_dropout:
        x = Dropout(0.2, name="dropout")(x)
    out = Dense(classes, activation="softmax", name="classifier")(x)
    return Model(inp, out, name="inception_v3")


Inception.v3 = staticmethod(inception_v3)
