"""Inception-v1 (GoogLeNet) — the reference's flagship ImageNet training
example (zoo/.../examples/inception/Train.scala:31-120 trains BigDL's
Inception_v1_NoAuxClassifier; python twin
pyzoo/zoo/examples/inception/inception.py:119-165).

NHWC graph built on the Model API: every inception block is four parallel
towers merged on the channel axis — all MXU convolutions in one XLA
program.  LRN layers match the reference's SpatialCrossMapLRN(5, 1e-4,
0.75) placement.
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    LRN2D,
    AveragePooling2D,
    Convolution2D,
    Dense,
    Dropout,
    Flatten,
    MaxPooling2D,
    Merge,
)

# (1x1, [3x3_reduce, 3x3], [5x5_reduce, 5x5], pool_proj) per block —
# inception.py:137-157 configs
_V1_BLOCKS = {
    "3a": (64, (96, 128), (16, 32), 32),
    "3b": (128, (128, 192), (32, 96), 64),
    "4a": (192, (96, 208), (16, 48), 64),
    "4b": (160, (112, 224), (24, 64), 64),
    "4c": (128, (128, 256), (24, 64), 64),
    "4d": (112, (144, 288), (32, 64), 64),
    "4e": (256, (160, 320), (32, 128), 128),
    "5a": (256, (160, 320), (32, 128), 128),
    "5b": (384, (192, 384), (48, 128), 128),
}


def _conv(x, filters, k, stride=1, name=None):
    return Convolution2D(filters, k, k, subsample=(stride, stride),
                         border_mode="same", activation="relu",
                         init="glorot_uniform", name=name)(x)


def _inception_block(x, key: str):
    """inception_layer_v1 (inception.py:83-117): 1x1 | 1x1->3x3 |
    1x1->5x5 | maxpool->1x1, channel-concat."""
    c1, (c3r, c3), (c5r, c5), cp = _V1_BLOCKS[key]
    p = f"inception_{key}/"
    t1 = _conv(x, c1, 1, name=p + "1x1")
    t2 = _conv(_conv(x, c3r, 1, name=p + "3x3_reduce"), c3, 3,
               name=p + "3x3")
    t3 = _conv(_conv(x, c5r, 1, name=p + "5x5_reduce"), c5, 5,
               name=p + "5x5")
    t4 = MaxPooling2D(pool_size=(3, 3), strides=(1, 1),
                      border_mode="same", name=p + "pool")(x)
    t4 = _conv(t4, cp, 1, name=p + "pool_proj")
    return Merge(mode="concat", concat_axis=-1, name=p + "output")(
        [t1, t2, t3, t4])


class Inception:
    """Factory namespace, like the reference companion objects."""

    @staticmethod
    def v1(classes: int = 1000, input_shape=(224, 224, 3),
           has_dropout: bool = True) -> Model:
        """Inception_v1_NoAuxClassifier
        (inception.py:119-165 layer-for-layer)."""
        inp = Input(shape=input_shape, name="input")
        x = _conv(inp, 64, 7, stride=2, name="conv1/7x7_s2")
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                         border_mode="same", name="pool1/3x3_s2")(x)
        x = LRN2D(alpha=1e-4, k=1.0, beta=0.75, n=5,
                  name="pool1/norm1")(x)
        x = _conv(x, 64, 1, name="conv2/3x3_reduce")
        x = _conv(x, 192, 3, name="conv2/3x3")
        x = LRN2D(alpha=1e-4, k=1.0, beta=0.75, n=5, name="conv2/norm2")(x)
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                         border_mode="same", name="pool2/3x3_s2")(x)
        x = _inception_block(x, "3a")
        x = _inception_block(x, "3b")
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                         border_mode="same", name="pool3/3x3_s2")(x)
        for key in ("4a", "4b", "4c", "4d", "4e"):
            x = _inception_block(x, key)
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                         border_mode="same", name="pool4/3x3_s2")(x)
        x = _inception_block(x, "5a")
        x = _inception_block(x, "5b")
        pool = input_shape[0] // 32
        x = AveragePooling2D(pool_size=(pool, pool), strides=(1, 1),
                             name="pool5")(x)
        x = Flatten()(x)
        if has_dropout:
            x = Dropout(0.4, name="pool5/drop")(x)
        out = Dense(classes, activation="softmax",
                    name="loss3/classifier")(x)
        return Model(inp, out, name="inception_v1")
