"""Event-file writers — reference tensorboard/FileWriter.scala:32-88 and the
TrainSummary/ValidationSummary API on KerasNet (Topology.scala:183-236,
including scalar read-back ``getTrainSummary("Loss"/"Throughput")``).
"""

from __future__ import annotations

import os
import socket
import threading
import time

from analytics_zoo_tpu.tensorboard.record import (
    decode_event_scalars,
    encode_event,
    encode_scalar_summary,
    read_records,
    write_record,
)


class FileWriter:
    """Appends Event protos to a tfevents file (FileWriter.scala:32-88)."""

    def __init__(self, log_dir: str, filename_suffix: str = ""):
        os.makedirs(log_dir, exist_ok=True)
        fname = "events.out.tfevents.%d.%s%s" % (
            int(time.time()), socket.gethostname(), filename_suffix
        )
        self.path = os.path.join(log_dir, fname)
        # writes AND close serialize on _lock: a concurrent _write either
        # completes before the close or sees closed-and-drops
        self._fh = open(self.path, "ab")  # guarded-by: _lock
        self._lock = threading.Lock()
        self._write(encode_event(file_version="brain.Event:2"))

    def _write(self, event: bytes):
        with self._lock:
            # a closed writer drops events instead of raising: serving's
            # run() closes its summary on loop exit, and a concurrently
            # finishing batch (or a later warm-up run() on the same server
            # object) must not crash on the trailing Throughput scalar
            if self._fh.closed:
                return
            write_record(self._fh, event)
            self._fh.flush()

    def add_scalar(self, tag: str, value: float, step: int):
        self._write(
            encode_event(step=step,
                         summary=encode_scalar_summary(tag, float(value)))
        )

    def close(self):
        # under the lock: a concurrent _write must either complete before
        # the close or observe closed-and-drop — never write a closed fh
        with self._lock:
            self._fh.close()

    @property
    def closed(self) -> bool:
        return self._fh.closed


class _SummaryBase:
    """A named sub-writer under <log_dir>/<app_name>/<kind> — mirrors the
    reference's TrainSummary/ValidationSummary directory convention."""

    kind = "train"

    def __init__(self, log_dir: str, app_name: str):
        self.dir = os.path.join(log_dir, app_name, self.kind)
        self._writer = FileWriter(self.dir)

    def add_scalar(self, tag: str, value: float, step: int):
        self._writer.add_scalar(tag, value, step)

    def read_scalar(self, tag: str):
        """Read back [(step, value, wall_time)] for a tag (reference
        ``getScalar``/``getTrainSummary`` Topology.scala:204-236)."""
        out = []
        for fname in sorted(os.listdir(self.dir)):
            if "tfevents" not in fname:
                continue
            with open(os.path.join(self.dir, fname), "rb") as fh:
                for rec in read_records(fh):
                    for wall, step, t, v in decode_event_scalars(rec):
                        if t == tag:
                            out.append((step, v, wall))
        return out

    def close(self):
        self._writer.close()

    @property
    def closed(self) -> bool:
        return self._writer.closed


class TrainSummary(_SummaryBase):
    kind = "train"


class ValidationSummary(_SummaryBase):
    kind = "validation"


class InferenceSummary(_SummaryBase):
    """Reference inference/InferenceSummary.scala."""

    kind = "inference"
