"""TFRecord framing + CRC32C + minimal Event/Summary protobuf encoding.

Reference: the in-repo TF event writer that needs no TF runtime —
zoo/.../tensorboard/{RecordWriter.scala, Summary.scala, EventWriter.scala,
FileWriter.scala:32-88} plus its CRC32C. Same trick here: hand-encode the
handful of proto fields TensorBoard actually reads, so the framework has no
tensorflow dependency.

A C-accelerated CRC32C from analytics_zoo_tpu.native is used when the
native library is built; the pure-python table fallback is always available.
"""

from __future__ import annotations

import struct
import time

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), table-driven
# ---------------------------------------------------------------------------

_POLY = 0x82F63B78
_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (_POLY if _c & 1 else 0)
    _TABLE.append(_c)


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _load_native():
    try:
        from analytics_zoo_tpu.native import lib as _native_lib

        if _native_lib is not None:
            return _native_lib.crc32c
    except Exception:
        pass
    return None


_native_crc = _load_native()


def crc32c(data: bytes) -> int:
    if _native_crc is not None:
        return _native_crc(data)
    return _crc32c_py(data)


def masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# TFRecord framing (RecordWriter.scala role)
# ---------------------------------------------------------------------------


def write_record(fh, data: bytes) -> None:
    header = struct.pack("<Q", len(data))
    fh.write(header)
    fh.write(struct.pack("<I", masked_crc(header)))
    fh.write(data)
    fh.write(struct.pack("<I", masked_crc(data)))


def read_records(fh):
    while True:
        header = fh.read(8)
        if len(header) < 8:
            return
        (length,) = struct.unpack("<Q", header)
        fh.read(4)  # header crc
        data = fh.read(length)
        fh.read(4)  # data crc
        yield data


# ---------------------------------------------------------------------------
# Protobuf encoding (Summary.scala role) — only what TB reads
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_bytes(num: int, data: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(data)) + data


def _field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3) + _varint(value)


def _field_double(num: int, value: float) -> bytes:
    return _varint((num << 3) | 1) + struct.pack("<d", value)


def _field_float(num: int, value: float) -> bytes:
    return _varint((num << 3) | 5) + struct.pack("<f", value)


def encode_scalar_summary(tag: str, value: float) -> bytes:
    """Summary{ value: [Value{ tag=1, simple_value=2 }] }"""
    val = _field_bytes(1, tag.encode()) + _field_float(2, float(value))
    return _field_bytes(1, val)


def encode_event(step: int = 0, wall_time: float | None = None,
                 summary: bytes | None = None,
                 file_version: str | None = None) -> bytes:
    """Event{ wall_time=1, step=2, file_version=3, summary=5 }"""
    out = _field_double(1, wall_time if wall_time is not None else
                        time.time())
    if step:
        out += _field_varint(2, int(step))
    if file_version is not None:
        out += _field_bytes(3, file_version.encode())
    if summary is not None:
        out += _field_bytes(5, summary)
    return out


# -- decoding (for scalar read-back, FileWriter read API role) --------------


def _iter_fields(data: bytes):
    i = 0
    n = len(data)
    while i < n:
        key = 0
        shift = 0
        while True:
            b = data[i]
            i += 1
            key |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        num, wire = key >> 3, key & 7
        if wire == 0:
            val = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                val |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield num, wire, val
        elif wire == 1:
            yield num, wire, data[i:i + 8]
            i += 8
        elif wire == 2:
            ln = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield num, wire, data[i:i + ln]
            i += ln
        elif wire == 5:
            yield num, wire, data[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")


def decode_event_scalars(data: bytes):
    """Yield (wall_time, step, tag, value) scalars from one Event proto."""
    wall_time, step, summary = 0.0, 0, None
    for num, wire, val in _iter_fields(data):
        if num == 1 and wire == 1:
            (wall_time,) = struct.unpack("<d", val)
        elif num == 2 and wire == 0:
            step = val
        elif num == 5 and wire == 2:
            summary = val
    if summary is None:
        return
    for num, wire, val in _iter_fields(summary):
        if num == 1 and wire == 2:  # Summary.Value
            tag, simple = None, None
            for n2, w2, v2 in _iter_fields(val):
                if n2 == 1 and w2 == 2:
                    tag = v2.decode()
                elif n2 == 2 and w2 == 5:
                    (simple,) = struct.unpack("<f", v2)
            if tag is not None and simple is not None:
                yield wall_time, step, tag, simple
