"""NNFrames example — DataFrame-native training (reference
pyzoo/zoo/examples/nnframes: NNEstimator/NNClassifier over Spark
DataFrames; pandas is the DataFrame substrate here) with an
autograd CustomLoss, the reference's custom-criterion capability.

Builds a DataFrame of image-like features, fits an NNClassifier, then
refits with a CustomLoss written as Variable math
(reference autograd/CustomLoss.scala).

Usage:
    python examples/nnframes/finetune.py --epochs 15
"""

import argparse

import numpy as np
import pandas as pd


def make_df(n=256, dim=8, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 2.0, size=(classes, dim))
    rows, labels = [], []
    for _ in range(n):
        c = int(rng.integers(classes))
        rows.append((centers[c] + rng.normal(0, 0.4, dim)).astype(
            np.float32))
        labels.append(c)
    return pd.DataFrame({"features": rows, "label": labels})


def run(epochs=15, batch_size=32):
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.autograd import CustomLoss
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam
    from analytics_zoo_tpu.pipeline.nnframes import NNClassifier

    init_zoo_context("nnframes finetune")
    df = make_df()

    def build():
        net = Sequential()
        net.add(Dense(16, input_shape=(8,), activation="relu"))
        net.add(Dense(3, activation="softmax"))
        return net

    # 1. stock criterion via the DataFrame estimator
    clf = NNClassifier(build()).set_optim_method(Adam(lr=0.01)) \
        .set_batch_size(batch_size).set_max_epoch(epochs)
    model = clf.fit(df)
    out = model.transform(df)
    acc = (out["prediction"].to_numpy() == df["label"].to_numpy()).mean()

    # 2. same task, custom criterion as arbitrary python math under jax
    # tracing (the CustomLoss.scala capability): MSE against one-hot
    def mse_onehot(y_true, y_pred):
        oh = jax.nn.one_hot(jnp.asarray(y_true).astype(jnp.int32), 3)
        return jnp.mean((y_pred - oh) ** 2, axis=-1)

    clf2 = NNClassifier(build(), criterion=CustomLoss(mse_onehot))
    clf2.set_optim_method(Adam(lr=0.01)) \
        .set_batch_size(batch_size).set_max_epoch(epochs)
    model2 = clf2.fit(df)
    out2 = model2.transform(df)
    acc2 = (out2["prediction"].to_numpy() == df["label"].to_numpy()).mean()
    return acc, acc2


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=15)
    args = ap.parse_args()
    acc, acc2 = run(args.epochs)
    print(f"NNClassifier accuracy: {acc:.3f}; "
          f"with autograd CustomLoss: {acc2:.3f}")


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
