"""Minimal DataFrame-native training (reference
pyzoo/zoo/examples/nnframes/tensorflow/SimpleTraining.py: an NNEstimator
over a two-column Spark DataFrame with a TF model; pandas is the
DataFrame substrate here, the model is zoo keras layers).

The smallest end-to-end nnframes flow: DataFrame in → NNEstimator.fit →
NNModel.transform adds the prediction column.

Usage: python examples/nnframes/simple_training.py [--epochs 20]
"""

import argparse
import os
import sys

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def make_df(n=384, seed=0):
    rng = np.random.default_rng(seed)
    xs, ys = [], []
    for _ in range(n):
        v = rng.uniform(-1, 1, size=2).astype(np.float32)
        xs.append(v)
        ys.append(int(v[0] * v[1] > 0))   # XOR-quadrant: needs the hidden
    return pd.DataFrame({"features": xs, "label": ys})


def run(epochs=40, batch_size=64):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.nnframes import NNClassifier

    init_zoo_context("nnframes simple training", seed=0)
    df = make_df()
    train_df, val_df = df[:320], df[320:]

    net = Sequential()
    net.add(Dense(16, activation="relu", input_shape=(2,)))
    net.add(Dense(2, activation="softmax"))

    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    clf = (NNClassifier(net)
           .set_optim_method(Adam(lr=0.01))
           .set_batch_size(batch_size)
           .set_max_epoch(epochs)
           .set_features_col("features")
           .set_label_col("label"))
    model = clf.fit(train_df)

    out = model.transform(val_df)
    acc = float((out["prediction"].to_numpy()
                 == val_df["label"].to_numpy()).mean())
    print("held-out accuracy:", round(acc, 3))
    print(out.head(3))
    return acc


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=40)
    a = ap.parse_args()
    acc = run(epochs=a.epochs)
    assert acc > 0.85, acc


if __name__ == "__main__":
    main()
