"""DataFrame transfer learning (reference
pyzoo/zoo/examples/nnframes/imageTransferLearning/
ImageTransferLearningExample.py: caffe Inception loaded with Net.load,
truncated with ``new_graph``, frozen, and a new Dense head trained by
NNClassifier over an image DataFrame).

Same recipe on the TPU-native stack: a small convnet pretrained here on
a 4-class image task stands in for the downloaded Inception (no network
in this sandbox); ``new_graph`` truncates it at the feature layer,
``freeze`` pins the backbone, and NNClassifier trains the binary head
from a pandas DataFrame of images read off disk by NNImageReader.

Usage: python examples/nnframes/transfer_learning.py [--epochs 15]
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def _write_images(root, n=96, size=16, seed=0):
    """Class = which image half carries the bright blob (PNGs on disk)."""
    import cv2

    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 2, size=n)
    os.makedirs(root, exist_ok=True)
    for i, lab in enumerate(labels):
        img = np.clip(rng.normal(70, 15, (size, size, 3)), 0,
                      255).astype(np.uint8)
        lo = 0 if lab == 0 else size // 2
        img[:, lo:lo + size // 2] = np.clip(
            img[:, lo:lo + size // 2] + 110.0, 0, 255).astype(np.uint8)
        cv2.imwrite(os.path.join(root, f"img_{i:03d}_{lab}.png"), img)
    return labels


def pretrain_backbone(size=16, seed=0, epochs=10):
    """Stand-in for the reference's downloaded Inception-V1."""
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D, Dense, Flatten, MaxPooling2D,
    )

    rng = np.random.default_rng(seed + 1)
    x = rng.normal(0.3, 0.15, size=(256, size, size, 3)).astype(np.float32)
    y = rng.integers(4, size=256).astype(np.int32)
    h = size // 2
    for i, c in enumerate(y):
        r, col = divmod(int(c), 2)
        x[i, r * h:(r + 1) * h, col * h:(col + 1) * h] += 0.5

    base = Sequential()
    base.add(Convolution2D(8, 3, 3, activation="relu",
                           input_shape=(size, size, 3)))
    base.add(MaxPooling2D((2, 2)))
    base.add(Flatten(name="feat"))
    base.add(Dense(4, activation="softmax"))
    base.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    base.fit(x, y, batch_size=64, nb_epoch=epochs)
    return base


def run(epochs=15, batch_size=32):
    import pandas as pd

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.nnframes import (
        NNClassifier,
        NNImageReader,
    )

    init_zoo_context("nnframes transfer learning", seed=0)
    root = tempfile.mkdtemp()
    labels = _write_images(root)

    # reference flow: readImages -> DataFrame with an image column
    df = NNImageReader.read_images(root)
    df["label"] = labels
    df["features"] = df["image"].map(
        lambda im: np.asarray(im, np.float32) / 255.0)

    # pretrained backbone -> truncate at the feature layer -> freeze
    base = pretrain_backbone()
    feat = base.new_graph("feat")

    model = Sequential()
    model.add(feat)
    model.add(Dense(2, activation="softmax"))
    model.freeze(feat.name)

    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    clf = (NNClassifier(model)
           .set_optim_method(Adam(lr=0.01))
           .set_batch_size(batch_size)
           .set_max_epoch(epochs))
    nn_model = clf.fit(df)

    out = nn_model.transform(df)
    acc = float((out["prediction"].to_numpy()
                 == df["label"].to_numpy()).mean())
    print("transfer-learning accuracy:", round(acc, 3))
    print("frozen layers:", model.frozen_layers)
    return acc


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=15)
    a = ap.parse_args()
    acc = run(epochs=a.epochs)
    assert acc > 0.85, acc


if __name__ == "__main__":
    main()
