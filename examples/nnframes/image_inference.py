"""DataFrame image inference (reference
pyzoo/zoo/examples/nnframes/imageInference/ImageInferenceExample.py:
NNImageReader.readImages -> preprocessing chain -> NNModel.transform
appends a prediction column).

Generates a small on-disk image set, trains a tiny classifier on the same
distribution, then runs the reference's inference flow over the
DataFrame.

Usage: python examples/nnframes/image_inference.py
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def _write_images(root, n=24, size=24, seed=0):
    """Class 0 = dark image, class 1 = bright image (PNG on disk)."""
    import cv2

    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 2, size=n)
    os.makedirs(root, exist_ok=True)
    for i, lab in enumerate(labels):
        base = 60 if lab == 0 else 190
        img = np.clip(base + rng.normal(0, 20, (size, size, 3)), 0,
                      255).astype(np.uint8)
        cv2.imwrite(os.path.join(root, f"img_{i:03d}_{lab}.png"), img)
    return labels


def run():
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D,
        Dense,
        Flatten,
    )
    from analytics_zoo_tpu.pipeline.nnframes import NNImageReader, NNModel

    init_zoo_context("nnframes image inference", seed=0)
    root = tempfile.mkdtemp()
    labels = _write_images(root)

    # train a tiny brightness classifier on the same generator
    rng = np.random.default_rng(1)
    ytr = rng.integers(0, 2, size=64).astype(np.int32)
    xtr = np.stack([
        np.clip((60 if lab == 0 else 190)
                + rng.normal(0, 20, (24, 24, 3)), 0, 255) / 255.0
        for lab in ytr
    ]).astype(np.float32)
    net = Sequential()
    net.add(Convolution2D(4, 3, 3, activation="relu",
                          input_shape=(24, 24, 3)))
    net.add(Flatten())
    net.add(Dense(2, activation="softmax"))
    net.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    net.fit(xtr, ytr, batch_size=16, nb_epoch=30)

    # the reference inference flow: read a DataFrame of images, transform
    df = NNImageReader.read_images(root)
    df["features"] = df["image"].map(
        lambda im: (np.asarray(im, np.float32) / 255.0))
    nn_model = NNModel(net).set_features_col("features").set_batch_size(8)
    out = nn_model.transform(df)
    pred = np.stack(out["prediction"].to_numpy())
    classes = pred.argmax(1)
    # file names carry the truth: img_<i>_<label>.png
    truth = np.array([int(os.path.basename(p).split("_")[2][0])
                      for p in df["origin"]])
    acc = float((classes == truth).mean())
    print(f"DataFrame inference accuracy over {len(df)} images: {acc:.2f}")
    return acc


def main():
    argparse.ArgumentParser(description=__doc__).parse_args()
    run()


if __name__ == "__main__":
    main()
