"""Finetune on top of a frozen PyTorch backbone (reference
pyzoo/zoo/examples/pytorch/train/resnet_finetune/resnet_finetune.py: a
torchvision ResNet wrapped in TorchNet as a frozen feature extractor, with
a trainable classifier head finetuned on cats-vs-dogs via NNClassifier).

TPU-native version: the torch module runs host-side through
``pure_callback`` (with torch autograd supplying the input gradient), the
jax head trains on device — same freeze-backbone/train-head recipe, no
JNI.  Offline-safe: a small randomly-initialized CNN stands in for the
torchvision download; point --script PATH at any TorchScript module to use
a real one.

Usage:
    python examples/pytorch/finetune.py --epochs 10
"""

import argparse

import numpy as np


def make_backbone(channels=8):
    """Stand-in pretrained backbone (reference downloads torchvision
    resnet; this image has no network access)."""
    import torch

    # deterministic "pretrained" weights regardless of who consumed the
    # torch global RNG before us (test-ordering flake otherwise)
    torch.manual_seed(0)
    return torch.nn.Sequential(
        torch.nn.Conv2d(3, channels, 3, padding=1),
        torch.nn.ReLU(),
        torch.nn.AdaptiveAvgPool2d(4),
        torch.nn.Flatten(),
    )


def run(epochs=10, n=256, size=16, batch_size=32, script=None):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.net import TorchNet

    init_zoo_context("pytorch finetune", seed=0)
    import torch

    class _NHWC(torch.nn.Module):
        """Adapter: zoo layers are NHWC, torch convs are NCHW."""

        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def forward(self, x):
            return self.inner(x.permute(0, 3, 1, 2))

    inner = torch.jit.load(script, map_location="cpu") if script \
        else make_backbone()
    backbone = TorchNet.from_pytorch(
        _NHWC(inner), input_shape=(size, size, 3))

    model = Sequential()
    model.add(backbone)          # frozen: torch params never update
    model.add(Dense(2, activation="softmax"))
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.default_rng(0)
    y = rng.integers(0, 2, size=n).astype(np.int32)
    # "cats vs dogs" stand-in: class-dependent red/blue dominance
    x = rng.random((n, size, size, 3)).astype(np.float32) * 0.6
    x[:, :, :, 0] += y[:, None, None] * 0.4
    x[:, :, :, 2] += (1 - y)[:, None, None] * 0.4
    model.fit(x, y, batch_size=batch_size, nb_epoch=epochs)
    return model.evaluate(x, y, batch_size=batch_size)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--script", default=None,
                    help="TorchScript backbone path (default: built-in)")
    args = ap.parse_args()
    print(run(epochs=args.epochs, script=args.script))


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
