"""Inference through a PyTorch model (reference
pyzoo/zoo/examples/pytorch/inference/predict.py: wrap a torchvision model
in TorchNet and run distributed predict over images).

TPU-native version: the torch module executes host-side via
``pure_callback`` inside the jitted graph; the surrounding batching /
mesh-sharded predict is the framework's.  Offline-safe: a small
deterministic CNN stands in for the torchvision download.

Usage: python examples/pytorch/predict.py [--n 64]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def make_model(classes=5):
    import torch

    torch.manual_seed(0)
    return torch.nn.Sequential(
        torch.nn.Conv2d(3, 8, 3, stride=2), torch.nn.ReLU(),
        torch.nn.AdaptiveAvgPool2d(1), torch.nn.Flatten(),
        torch.nn.Linear(8, classes), torch.nn.Softmax(dim=1),
    ).eval()


def run(n=64, size=32):
    import torch

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.net import TorchNet

    init_zoo_context("pytorch predict", seed=0)
    module = make_model()
    net = TorchNet.from_pytorch(module, input_shape=(3, size, size))
    m = Sequential()
    m.add(net)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 3, size, size)).astype(np.float32)
    probs = np.asarray(m.predict(x))

    with torch.no_grad():
        ref = module(torch.from_numpy(x)).numpy()
    err = float(np.max(np.abs(probs - ref)))
    agree = float((probs.argmax(1) == ref.argmax(1)).mean())
    print(f"predicted {probs.shape}; max |zoo - torch| = {err:.2e}; "
          f"argmax agreement {agree:.2f}")
    return err, agree


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=64)
    a = p.parse_args()
    run(n=a.n)


if __name__ == "__main__":
    main()
