"""Minimal torch-interop training loop (reference
pyzoo/zoo/examples/pytorch/train/SimpleTrainingExample.py: a two-layer
nn.Module + nn.MSELoss wrapped in TorchNet/TorchCriterion, fitted with
the zoo Estimator on a toy regression).

The torch pieces play the same roles here: the torch ``nn.MSELoss`` IS
the training objective (TorchCriterion host callback with torch-autograd
gradients), and at the end the torch module — wrapped as a frozen
TorchNet — checks the learned function against the torch-side oracle.

Usage: python examples/pytorch/simple_training.py [--epochs 30]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def run(epochs=30, n=512, batch_size=64):
    import torch

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.net import TorchCriterion

    init_zoo_context("pytorch simple training", seed=0)
    rng = np.random.default_rng(0)
    x = rng.uniform(-1, 1, size=(n, 2)).astype(np.float32)
    # target: a fixed nonlinear map (the reference fits y = x W + noise)
    y = (np.sin(2 * x[:, :1]) + 0.5 * x[:, 1:] ** 2).astype(np.float32)

    m = Sequential()
    m.add(Dense(32, activation="relu", input_shape=(2,)))
    m.add(Dense(1))

    crit = TorchCriterion.from_pytorch(torch.nn.MSELoss())
    m.compile(optimizer="adam", loss=crit)
    m.fit(x, y, batch_size=batch_size, nb_epoch=epochs)

    pred = np.asarray(m.predict(x, batch_size=batch_size))
    mse = float(np.mean((pred - y) ** 2))
    # same number the torch loss would report
    with torch.no_grad():
        torch_mse = float(torch.nn.MSELoss()(
            torch.from_numpy(pred), torch.from_numpy(y)))
    print(f"final mse {mse:.4f} (torch-criterion view {torch_mse:.4f})")
    return mse


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=30)
    a = ap.parse_args()
    mse = run(epochs=a.epochs)
    assert mse < 0.05, mse


if __name__ == "__main__":
    main()
