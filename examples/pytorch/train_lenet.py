"""Train a torch-defined LeNet distributed (reference
pyzoo/zoo/examples/pytorch/train/Lenet_mnist.py: an nn.Module LeNet +
F.nll_loss wrapped in TorchNet/TorchCriterion, trained by the zoo
Estimator over Spark).

TPU re-design: torch modules are NOT trainable from the jax side (the
host-callback path computes input grads only, matching the reference's
frozen TorchNet), so the idiomatic flow is the one this example shows:

1. define the model in torch, take its (seeded) initial ``state_dict``;
2. ``import_state_dict`` those tensors into the equivalent zoo layers;
3. train the zoo model on-device — with the torch loss itself running as
   the training objective through ``TorchCriterion`` (host callback with
   torch-autograd gradients), the reference's criterion capability.

Usage: python examples/pytorch/train_lenet.py [--epochs 10]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def digits_data():
    from sklearn.datasets import load_digits

    d = load_digits()
    x = (d.images[:, None, :, :] / 16.0).astype(np.float32)  # NCHW like torch
    y = d.target.astype(np.int32)
    n = (int(len(x) * 0.85) // 64) * 64
    return (x[:n], y[:n]), (x[n:], y[n:])


def make_torch_lenet():
    import torch

    torch.manual_seed(0)

    class LeNet(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = torch.nn.Conv2d(1, 6, 3, padding=1)
            self.conv2 = torch.nn.Conv2d(6, 16, 3)
            self.fc1 = torch.nn.Linear(16 * 2 * 2, 32)
            self.fc2 = torch.nn.Linear(32, 10)

        def forward(self, x):
            x = torch.relu(self.conv1(x))
            x = torch.max_pool2d(x, 2)
            x = torch.relu(self.conv2(x))
            x = torch.flatten(x, 1)
            x = torch.relu(self.fc1(x))
            return torch.log_softmax(self.fc2(x), dim=1)

    return LeNet()


def run(epochs=10, batch_size=64):
    import torch
    import torch.nn.functional as F

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Activation, Convolution2D, Dense, Flatten, MaxPooling2D, Permute,
    )
    from analytics_zoo_tpu.pipeline.api.net import (
        TorchCriterion,
        import_state_dict,
    )

    init_zoo_context("pytorch train_lenet", seed=0)
    (xt, yt), (xv, yv) = digits_data()
    torch_model = make_torch_lenet()

    # the zoo equivalent (HWC convs; Permute adapts the NCHW input)
    m = Sequential()
    m.add(Permute((2, 3, 1), input_shape=(1, 8, 8)))     # NCHW -> NHWC
    m.add(Convolution2D(6, 3, 3, activation="relu", border_mode="same",
                        name="c1"))
    m.add(MaxPooling2D((2, 2)))
    m.add(Convolution2D(16, 3, 3, activation="relu", name="c2"))
    m.add(Flatten())
    m.add(Dense(32, activation="relu", name="fc1"))
    m.add(Dense(10, name="fc2"))
    m.add(Activation("log_softmax"))

    # torch's seeded init -> zoo params (OIHW -> HWIO for convs; (out,in)
    # -> (in,out) for linears; fc1 additionally reorders the flattened
    # CHW feature axis to the zoo model's HWC flatten order)
    sd = torch_model.state_dict()
    oihw = lambda a: np.transpose(a, (2, 3, 1, 0))  # noqa: E731
    t = lambda a: a.T  # noqa: E731

    def fc1_remap(a):  # (32, C*H*W) -> (H*W*C, 32) in HWC order
        a = a.reshape(32, 16, 2, 2)           # (out, C, H, W)
        a = np.transpose(a, (2, 3, 1, 0))     # (H, W, C, out)
        return a.reshape(2 * 2 * 16, 32)

    import_state_dict(m, sd, [
        ("c1/kernel", "conv1.weight", oihw),
        ("c1/bias", "conv1.bias"),
        ("c2/kernel", "conv2.weight", oihw),
        ("c2/bias", "conv2.bias"),
        ("fc1/kernel", "fc1.weight", fc1_remap),
        ("fc1/bias", "fc1.bias"),
        ("fc2/kernel", "fc2.weight", t),
        ("fc2/bias", "fc2.bias"),
    ])

    # sanity: identical forward before training
    with torch.no_grad():
        want = torch_model(torch.from_numpy(xv[:8])).numpy()
    got = np.asarray(m.predict(xv[:8], batch_size=8))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    print("zoo model reproduces the torch forward: max err",
          float(np.abs(got - want).max()))

    # torch F.nll_loss as the training objective (TorchCriterion)
    crit = TorchCriterion.from_pytorch(
        lambda pred, target: F.nll_loss(pred, target.long()))
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    m.compile(optimizer=Adam(lr=0.01), loss=crit, metrics=["accuracy"])
    m.fit(xt, yt, batch_size=batch_size, nb_epoch=epochs)
    metrics = m.evaluate(xv, yv, batch_size=batch_size)
    print("val:", {k: round(float(v), 4) for k, v in metrics.items()})
    return metrics


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=10)
    a = ap.parse_args()
    metrics = run(epochs=a.epochs)
    assert metrics["accuracy"] > 0.9, metrics


if __name__ == "__main__":
    main()
