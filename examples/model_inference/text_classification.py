"""Text-classification inference app — train, simple driver, web service.

Mirror of the reference apps `model-inference-examples/
text-classification-training` (TextClassificationTrainer.scala: train a
CNN text classifier, save for deployment) and `text-classification-
inference` (TextClassificationModel.java: an AbstractInferenceModel
subclass owning the text preprocess; SimpleDriver.java: batch predict;
WebServiceDriver.java + WebServiceController.java: an HTTP POST /predict
endpoint).  The JVM/Spring stack becomes: InferenceModel subclass with
the preprocess inside, plus a stdlib http.server service.

Usage:
    python examples/model_inference/text_classification.py train --out d/
    python examples/model_inference/text_classification.py simple --dir d/
    python examples/model_inference/text_classification.py serve --dir d/
"""

import argparse
import json
import os
import threading

import numpy as np

SEQUENCE_LENGTH = 100
TOKEN_LENGTH = 64


def _corpus(n_classes=4, n_docs=400, seed=0):
    # class-specific token families (same synthetic scheme as
    # examples/textclassification/train.py — no news20 archive in sandbox)
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    for _ in range(n_docs):
        c = int(rng.integers(n_classes))
        words = [f"w{c}_{int(rng.integers(30))}" for _ in range(20)] \
            + [f"c{int(rng.integers(50))}" for _ in range(10)]
        rng.shuffle(words)
        texts.append(" ".join(words))
        labels.append(c)
    return texts, labels, n_classes


def train_and_save(out_dir, epochs=10, encoder="cnn"):
    """The text-classification-training app: fit and export model +
    word index for the inference side (TextClassificationTrainer.scala
    saves the bigdl model; we also persist the dictionary)."""
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.feature.text import TextSet
    from analytics_zoo_tpu.models.textclassification import TextClassifier

    init_zoo_context("text-classification-training", seed=0)
    texts, labels, n_classes = _corpus()
    ts = TextSet.from_texts(texts, labels).tokenize().normalize() \
        .word2idx(max_words_num=20000).shape_sequence(SEQUENCE_LENGTH)
    model = TextClassifier(
        class_num=n_classes, token_length=TOKEN_LENGTH,
        sequence_length=SEQUENCE_LENGTH, encoder=encoder,
        vocab_size=len(ts.get_word_index()) + 1)
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(ts.to_feature_set(), batch_size=32, nb_epoch=epochs)
    acc = model.evaluate(ts.to_feature_set(), batch_size=32)["accuracy"]
    os.makedirs(out_dir, exist_ok=True)
    model.save_model(os.path.join(out_dir, "text-classification.zoo"))
    ts.save_word_index(os.path.join(out_dir, "word_index.txt"))
    return acc


class TextClassificationModel:
    """The inference-side model: preprocess lives WITH the model
    (reference TextClassificationModel.java extends AbstractInferenceModel
    and owns tokenize→index→pad), predict goes through the pooled
    InferenceModel runner."""

    def __init__(self, model_dir, concurrent_num=4):
        from analytics_zoo_tpu.feature.text import TextSet
        from analytics_zoo_tpu.pipeline.inference import InferenceModel

        self._inference = InferenceModel(concurrent_num=concurrent_num)
        self._inference.load(
            os.path.join(model_dir, "text-classification.zoo"))
        self._word_index = TextSet.from_texts([]).load_word_index(
            os.path.join(model_dir, "word_index.txt")).get_word_index()

    def preprocess(self, text):
        """text -> (SEQUENCE_LENGTH,) int32 ids (reference
        TextProcessor.java: tokenize, stopword-strip, index, pad)."""
        from analytics_zoo_tpu.feature.text import TextSet

        ts = TextSet.from_texts([text]).tokenize().normalize() \
            .word2idx(existing_map=self._word_index) \
            .shape_sequence(SEQUENCE_LENGTH)
        return ts.features[0].indices.astype(np.int32)

    def predict(self, texts):
        batch = np.stack([self.preprocess(t) for t in texts])
        return np.asarray(self._inference.predict(batch))


def run_simple(model_dir, texts=None):
    """SimpleDriver.java: load once, predict a couple of documents."""
    model = TextClassificationModel(model_dir)
    if texts is None:
        raw, labels, _ = _corpus(n_docs=8, seed=7)
        texts = raw[:4]
    probs = model.predict(texts)
    preds = probs.argmax(axis=1)
    for t, p, pr in zip(texts, preds, probs):
        print(f"pred={int(p)} probs={np.round(pr, 3).tolist()} "
              f"text={t[:40]}...")
    return probs


def serve(model_dir, port=0, background=True):
    """WebServiceDriver.java: HTTP service, POST /predict with a JSON
    body {"text": ...} (or a list) -> class probabilities.  With
    ``background=True`` returns the live server (callers/tests post
    against it and shut it down); otherwise blocks in serve_forever."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    model = TextClassificationModel(model_dir)

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            if self.path != "/predict":
                self.send_error(404)
                return
            try:
                body = json.loads(self.rfile.read(
                    int(self.headers.get("Content-Length", 0))))
                texts = body["text"]
                if isinstance(texts, str):
                    texts = [texts]
                probs = model.predict(texts)
                out = {"predictions": probs.argmax(1).tolist(),
                       "probabilities": probs.tolist()}
                data = json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            except Exception as e:  # noqa: BLE001 — surface as HTTP 400
                self.send_error(400, str(e))

        def log_message(self, *a):  # quiet CI
            pass

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    if not background:
        print(f"serving on :{server.server_address[1]} — POST /predict")
        server.serve_forever()
        return server
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server


def post_predict(port, texts):
    """A minimal client for the web service (the reference README's
    curl call)."""
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"text": texts}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read())


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("mode", choices=("train", "simple", "serve"))
    ap.add_argument("--dir", default="/tmp/zoo_text_classification")
    ap.add_argument("--out", default=None)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--port", type=int, default=8080)
    args = ap.parse_args()
    if args.mode == "train":
        acc = train_and_save(args.out or args.dir, epochs=args.epochs)
        print("train accuracy:", round(acc, 4))
    elif args.mode == "simple":
        run_simple(args.dir)
    else:
        serve(args.dir, port=args.port, background=False)


if __name__ == "__main__":
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    main()
