"""Flink-style streaming image classification through InferenceModel.

Mirror of the reference app `model-inference-examples/model-inference-
flink/.../Resnet50ImageClassification/`: `ImageClassificationStreaming`
builds a Flink `StreamExecutionEnvironment`, maps the image stream
through `Resnet50InferenceModel` — a `RichMapFunction` whose `open()`
loads the model into an InferenceModel, `map()` preprocesses (mean
subtract, scale, channel-reverse) + predicts, and `close()` releases it —
and collects the class labels.

TPU-native version: the stream operator has the same open/map/close
lifecycle over the pooled jit InferenceModel, the source is a watched
spool directory of frames (the streaming idiom used across examples/
streaming), and the model is an ImageClassifier with its per-family
preprocess config (mean/scale/channel handling live in the config chain,
reference ImageProcesser.scala).

Usage:
    python examples/model_inference/streaming_image_classification.py
"""

import argparse
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def make_dataset(n=320, size=32, seed=0):
    """Classifiable synthetic frames: class = brightest quadrant."""
    import numpy as np

    rng = np.random.default_rng(seed)
    x = rng.normal(100, 20, (n, size, size, 3)).astype(np.float32)
    y = rng.integers(0, 4, n)
    h = size // 2
    for i, c in enumerate(y):
        r0, c0 = (c // 2) * h, (c % 2) * h
        x[i, r0:r0 + h, c0:c0 + h] += 80
    return np.clip(x, 0, 255), y.astype(np.int32)


class ImageClassificationMapFunction:
    """The RichMapFunction (reference Resnet50InferenceModel.scala):
    open() -> load model into InferenceModel; map() -> preprocess +
    predict + label; close() -> drop the handle."""

    def __init__(self, model_path, label_map, mean, scale):
        self.model_path = model_path
        self.label_map = label_map
        self.mean = mean
        self.scale = scale
        self._inference = None

    def open(self):
        from analytics_zoo_tpu.pipeline.inference import InferenceModel

        self._inference = InferenceModel(concurrent_num=2).load(
            self.model_path)

    def map(self, frame):
        import numpy as np

        if self._inference is None:
            raise RuntimeError("open() not called")
        x = (frame.astype(np.float32) - self.mean) * self.scale
        probs = np.asarray(self._inference.predict(x[None]))[0]
        top = int(probs.argmax())
        return self.label_map[top], float(probs[top])

    def close(self):
        self._inference = None


def run(epochs=25, n_stream=6, size=32, spool_dir=None):
    """Train a small classifier, save it, then stream frames through the
    map function exactly like the Flink job's source->map->sink chain."""
    import numpy as np

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.image.imageclassification import (
        ImageClassifier,
    )
    from analytics_zoo_tpu.models.lenet import build_lenet

    init_zoo_context("flink-style image classification", seed=0)
    x, y = make_dataset(size=size)
    labels = ["top-left", "top-right", "bottom-left", "bottom-right"]
    mean, scale = 127.0, 1.0 / 64.0

    net = build_lenet(classes=4, input_shape=(size, size, 3))
    clf = ImageClassifier(model=net)
    clf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    clf.fit((x - mean) * scale, y, batch_size=32, nb_epoch=epochs)
    model_dir = tempfile.mkdtemp(prefix="zoo_flink_app_")
    model_path = os.path.join(model_dir, "classifier.zoo")
    clf.save_model(model_path)

    spool = spool_dir or tempfile.mkdtemp(prefix="zoo_stream_src_")
    os.makedirs(spool, exist_ok=True)

    def source():
        # the Flink source: frames arrive over time
        for i in range(n_stream):
            tmp = os.path.join(spool, f".tmp-{i}.npy")
            np.save(tmp, x[i])
            os.replace(tmp, os.path.join(spool, f"frame-{i}.npy"))
            time.sleep(0.05)

    op = ImageClassificationMapFunction(model_path, labels, mean, scale)
    op.open()
    feeder = threading.Thread(target=source, daemon=True)
    feeder.start()

    def frame_idx(fname):
        return int(fname.split("-")[1].split(".")[0])

    results, seen = {}, set()
    deadline = time.monotonic() + 120
    while len(results) < n_stream and time.monotonic() < deadline:
        # only completed frames: the feeder writes .tmp-*.npy then
        # os.replace()s to frame-*.npy atomically
        pending = sorted((f for f in os.listdir(spool)
                          if f.startswith("frame-") and f not in seen),
                         key=frame_idx)
        if not pending:
            time.sleep(0.05)
            continue
        for fname in pending:
            seen.add(fname)
            frame = np.load(os.path.join(spool, fname))
            results[fname] = op.map(frame)
    feeder.join()
    op.close()

    truth = [labels[int(c)] for c in y[:n_stream]]
    ordered = sorted(results.items(), key=lambda kv: frame_idx(kv[0]))
    for i, (fname, (label, p)) in enumerate(ordered):
        print(f"{fname}: {label} ({p:.3f}) truth={truth[i]}")
    return results, truth


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=25)
    ap.add_argument("--n-stream", type=int, default=6)
    args = ap.parse_args()
    run(epochs=args.epochs, n_stream=args.n_stream)


if __name__ == "__main__":
    main()
