"""NCF recommendation inference app.

Mirror of the reference app `model-inference-examples/
recommendation-inference`: NueralCFModel.scala / NueralCFJModel.java load
a pre-trained NeuralCF into an (Abstract)InferenceModel, `preProcess`
turns a `List<UserItemPair>` into input tensors, and SimpleDriver
predicts pairs (1,2)..(9,10) and prints the scores.

Usage:
    python examples/model_inference/recommendation_inference.py \
        [--model-path p] [--train-first]
"""

import argparse
import os

import numpy as np


def train_and_save(model_path, n_users=40, n_items=60, epochs=12, seed=0):
    """Produce the pre-trained ncf model the reference assumes exists
    (its README points at a model trained by the recommendation example)."""
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.recommendation import NeuralCF

    init_zoo_context("ncf-training", seed=seed)
    rng = np.random.default_rng(seed)
    # preference structure: user u likes item i iff (u + i) % 3 == 0
    users = rng.integers(0, n_users, 4096)
    items = rng.integers(0, n_items, 4096)
    labels = ((users + items) % 3 == 0).astype(np.int32)
    ncf = NeuralCF(user_count=n_users, item_count=n_items, class_num=2,
                   hidden_layers=(20, 10))
    ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    ncf.fit([users, items], labels, batch_size=256, nb_epoch=epochs)
    ncf.save_model(model_path)
    return ncf.evaluate([users, items], labels, batch_size=256)["accuracy"]


class NeuralCFInferenceModel:
    """Reference NueralCFJModel: wraps InferenceModel, owns the
    UserItemPair -> tensor preprocess."""

    def __init__(self, concurrent_num=4):
        from analytics_zoo_tpu.pipeline.inference import InferenceModel

        self._inference = InferenceModel(concurrent_num=concurrent_num)

    def load(self, model_path):
        self._inference.load(model_path)
        return self

    @staticmethod
    def pre_process(user_item_pairs):
        """List of (user, item) -> the model's two int input arrays
        (reference preProcess builds List<List<JTensor>>)."""
        pairs = np.asarray(list(user_item_pairs), np.int32)
        return [pairs[:, 0], pairs[:, 1]]

    def predict(self, user_item_pairs):
        inputs = self.pre_process(user_item_pairs)
        return np.asarray(self._inference.predict(inputs))


def run(model_path=None, train_first=True):
    """SimpleDriver.java: load, predict pairs (1,2)..(9,10), print."""
    model_path = model_path or "/tmp/zoo_ncf_inference/ncf.zoo"
    os.makedirs(os.path.dirname(model_path), exist_ok=True)
    train_acc = None
    if train_first or not os.path.exists(model_path):
        train_acc = train_and_save(model_path)
    rcm = NeuralCFInferenceModel().load(model_path)
    pairs = [(i, i + 1) for i in range(1, 10)]
    probs = rcm.predict(pairs)
    for (u, it), p in zip(pairs, probs):
        print(f"user={u} item={it} scores={np.round(p, 4).tolist()}")
    return train_acc, probs


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model-path", default=None)
    ap.add_argument("--train-first", action="store_true",
                    help="retrain and overwrite even if the model exists "
                         "(a missing model always trains)")
    args = ap.parse_args()
    run(args.model_path, args.train_first)


if __name__ == "__main__":
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    main()
