"""Image-classification zoo predict (reference
pyzoo/zoo/examples/imageclassification/predict.py: load an ImageClassifier
zoo model, read an image folder into an ImageSet, predict with the model's
preprocess config, print LabelOutput top-k).

Self-contained: trains a small classifier on synthetic images (class =
bright vs dark), then runs the zoo predict path — ImageSet ->
config preprocessing -> batched predict -> (label, prob) top-k.  Pass
--image-dir to classify your own images instead.

Usage:
    python examples/imageclassification/predict.py --topk 2
"""

import argparse

import numpy as np


def run(n=6, size=28, topk=2, image_dir=None, epochs=10):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.feature.image import ImageSet
    from analytics_zoo_tpu.models.image.imageclassification import (
        ImageClassifier,
        ImageClassificationConfig,
    )
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D,
        Dense,
        Flatten,
        MaxPooling2D,
    )

    init_zoo_context("imageclassification predict")

    # tiny trainable classifier standing in for a downloaded zoo model
    net = Sequential()
    net.add(Convolution2D(8, 3, 3, activation="relu",
                          input_shape=(size, size, 3)))
    net.add(MaxPooling2D())
    net.add(Flatten())
    net.add(Dense(2, activation="softmax"))
    net.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    def make_images(k, seed):
        r = np.random.default_rng(seed)
        y = r.integers(0, 2, size=k).astype(np.int32)
        # class 1 = bright: a clear brightness offset, not a knife-edge
        x = (r.random((k, size, size, 3)) * 0.5 +
             y[:, None, None, None] * 0.45).astype(np.float32)
        return x, y

    x, y = make_images(256, 0)
    net.fit(x, y, batch_size=32, nb_epoch=epochs)

    config = ImageClassificationConfig(
        resize=size, crop=size, mean=(0.0, 0.0, 0.0), std=(1.0, 1.0, 1.0),
        label_map={0: "dark", 1: "bright"})
    clf = ImageClassifier(model=net, config=config)

    if image_dir:
        image_set = ImageSet.read(image_dir)
        truths = None
    else:
        imgs, ytrue = make_images(n, 1)
        truths = ["bright" if c else "dark" for c in ytrue]
        image_set = ImageSet.from_arrays(imgs)
    labeled = clf.predict_image_set(image_set, top_k=topk)
    return labeled, truths


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--image-dir", default=None)
    ap.add_argument("--topk", type=int, default=2)
    args = ap.parse_args()
    labeled, truths = run(topk=args.topk, image_dir=args.image_dir)
    for i, preds in enumerate(labeled):
        truth = f"  (true: {truths[i]})" if truths else ""
        top = ", ".join(f"{name}={p:.2f}" for name, p in preds)
        print(f"image {i}: {top}{truth}")


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
