"""NeuralCF recommendation example — movielens-style (reference
pyzoo/zoo/examples/recommendation/ncf_explicit_example.py: ratings ->
NeuralCF -> fit -> recommend_for_user).

With --ratings, expects MovieLens ``user::item::rating::ts`` lines.
Without, synthetic ratings with planted user/item affinity blocks.

Usage:
    python examples/recommendation/neuralcf.py --epochs 8
"""

import argparse

import numpy as np


def load_ratings(path=None, n_users=200, n_items=100, n=6000, seed=0):
    if path:
        users, items, ratings = [], [], []
        with open(path) as f:
            for line in f:
                u, i, r, *_ = line.strip().split("::")
                users.append(int(u) - 1)
                items.append(int(i) - 1)
                ratings.append(float(r))
        users, items = np.asarray(users), np.asarray(items)
        labels = (np.asarray(ratings) >= 4).astype(np.int32)  # implicit
        return users, items, labels, users.max() + 1, items.max() + 1
    # synthetic: users like items in their own block
    rng = np.random.default_rng(seed)
    users = rng.integers(0, n_users, size=n)
    items = rng.integers(0, n_items, size=n)
    affinity = (users % 4) == (items % 4)
    noise = rng.random(n) < 0.1
    labels = (affinity ^ noise).astype(np.int32)
    return users.astype(np.int32), items.astype(np.int32), labels, \
        n_users, n_items


def run(ratings=None, epochs=8, batch_size=256):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.recommendation import NeuralCF

    init_zoo_context("neuralcf")
    users, items, labels, n_users, n_items = load_ratings(ratings)
    n_train = int(0.9 * len(users))
    ncf = NeuralCF(user_count=n_users, item_count=n_items, class_num=2,
                   hidden_layers=(40, 20, 10))
    ncf.compile(optimizer="adam",
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    ncf.fit([users[:n_train], items[:n_train]], labels[:n_train],
            batch_size=batch_size, nb_epoch=epochs)
    results = ncf.evaluate([users[n_train:], items[n_train:]],
                           labels[n_train:], batch_size=batch_size)
    recs = ncf.recommend_for_user(
        user_id=0, candidate_items=np.arange(n_items), max_items=5)
    return results, recs


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ratings", default=None,
                    help="movielens ratings.dat (default: synthetic)")
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=256)
    args = ap.parse_args()
    results, recs = run(args.ratings, args.epochs, args.batch_size)
    print("test:", {k: round(v, 4) for k, v in results.items()})
    print("top-5 items for user 0:", recs)


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
