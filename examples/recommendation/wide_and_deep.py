"""Wide & Deep training script (reference
pyzoo/zoo/examples and apps recommendation-wide-n-deep: ColumnFeatureInfo
-> WideAndDeep -> fit -> predictUserItemPair; the notebook variant lives
at apps/wide_n_deep.ipynb).

Usage: python examples/recommendation/wide_and_deep.py [--epochs 12]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def make_interactions(n=2048, n_users=40, n_items=60, n_genres=4, seed=0):
    rng = np.random.default_rng(seed)
    user_pref = rng.integers(0, n_genres, size=n_users)
    item_genre = rng.integers(0, n_genres, size=n_items)
    users = rng.integers(0, n_users, size=n)
    items = rng.integers(0, n_items, size=n)
    match = (user_pref[users] == item_genre[items]).astype(np.int32)
    noise = rng.random(n) < 0.1
    labels = np.where(noise, 1 - match, match).astype(np.int32)
    age = rng.uniform(18, 70, size=n).astype(np.float32)
    rows = {"user": users, "item": items, "genre": item_genre[items],
            "age": (age - 44.0) / 26.0}
    return rows, labels


def run(epochs=12):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.recommendation import (
        ColumnFeatureInfo,
        WideAndDeep,
        to_wide_deep_features,
    )

    init_zoo_context("wide and deep", seed=0)
    rows, labels = make_interactions()
    info = ColumnFeatureInfo(
        wide_base_cols=["user", "item"], wide_base_dims=[40, 60],
        wide_cross_cols=["genre"], wide_cross_dims=[4],
        indicator_cols=["genre"], indicator_dims=[4],
        embed_cols=["user", "item"], embed_in_dims=[40, 60],
        embed_out_dims=[8, 8],
        continuous_cols=["age"],
    )
    features = to_wide_deep_features(rows, info)
    model = WideAndDeep(model_type="wide_n_deep", class_num=2,
                        column_info=info, hidden_layers=(32, 16))
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    n_train = 1536
    model.fit([f[:n_train] for f in features], labels[:n_train],
              batch_size=64, nb_epoch=epochs)
    acc = model.evaluate([f[n_train:] for f in features], labels[n_train:],
                         batch_size=64)["accuracy"]
    pair_probs = model.predict_user_item_pair(
        [f[n_train:n_train + 64] for f in features])
    print(f"held-out accuracy {acc:.3f}; "
          f"first pair scores {np.round(pair_probs[:4], 3)}")
    return float(acc)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=12)
    a = p.parse_args()
    run(epochs=a.epochs)


if __name__ == "__main__":
    main()
