"""Custom losses and Lambda layers via autograd (reference
pyzoo/zoo/examples/autograd/{custom.py,customloss.py}: fit y = 2x1 + 2x2 +
0.4 with a user-defined mean-absolute-error loss and a Lambda layer).

The reference builds a BigDL criterion graph from symbolic Variables; here
the same user function runs under jax tracing and jax.grad differentiates
it — no hand-written backward.

Usage:
    python examples/autograd/customloss.py --epochs 60
"""

import argparse

import numpy as np


def mean_absolute_error(y_true, y_pred):
    import jax.numpy as jnp

    return jnp.mean(jnp.abs(y_true - y_pred), axis=1)


def run(epochs=60, n=1000, batch_size=32):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.autograd import CustomLoss, Lambda
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import SGD

    init_zoo_context("autograd example")
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, (n, 2)).astype(np.float32)
    y = ((2 * x).sum(1) + 0.4).reshape(n, 1).astype(np.float32)

    model = Sequential()
    # Lambda layer: feature scaling as part of the graph (reference
    # custom.py uses Lambda for an elementwise expression).
    model.add(Lambda(lambda t: t * 2.0 - 1.0, input_shape=(2,)))
    model.add(Dense(1))
    model.compile(optimizer=SGD(lr=1e-2),
                  loss=CustomLoss(mean_absolute_error))
    model.fit(x, y, batch_size=batch_size, nb_epoch=epochs)

    dense_key = next(k for k in model.params if "dense" in k)
    w = np.asarray(model.params[dense_key]["kernel"]).ravel()
    b = float(np.asarray(model.params[dense_key]["bias"])[0])
    pred = model.predict(x[:4])
    mae = float(np.abs(model.predict(x) - y).mean())
    return {"kernel": w, "bias": b, "mae": mae, "pred": pred}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=60)
    args = ap.parse_args()
    r = run(epochs=args.epochs)
    # x is scaled to 2x-1 by the Lambda, so kernel converges to ~[1, 1]
    # and bias to ~2.4 (= 0.4 + 2*sum(0.5)*2 - offset): report the fit.
    print(f"kernel={r['kernel']}, bias={r['bias']:.3f}, mae={r['mae']:.4f}")


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
