"""Custom layer via the autograd Lambda facade (reference
pyzoo/zoo/examples/autograd/custom.py: a Lambda-built ``add_one_layer``
inside a Sequential trained on a synthetic regression).

Usage: python examples/autograd/custom.py [--epochs 30]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def run(epochs=30, n=512):
    import jax.numpy as jnp

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api import autograd as A
    from analytics_zoo_tpu.pipeline.api.autograd import CustomLoss, Lambda
    from analytics_zoo_tpu.pipeline.api.keras import Input, Model
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    init_zoo_context("autograd custom layer", seed=0)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    w_true = rng.normal(size=(4, 1)).astype(np.float32)
    y = (x @ w_true + 1.0).astype(np.float32)  # the +1 the Lambda learns

    inp = Input(shape=(4,))
    h = Dense(1)(inp)
    # the reference's "add_one_layer": a custom op with no weights
    out = Lambda(lambda v: v + 1.0)(h)
    model = Model(inp, out)

    def mae(y_true, y_pred):
        return A.mean(A.abs(y_true - y_pred), axis=1)

    model.compile(optimizer=Adam(lr=0.05), loss=CustomLoss(mae, [1]))
    model.fit(x, y, batch_size=32, nb_epoch=epochs)
    pred = np.asarray(model.predict(x))
    err = float(np.mean(np.abs(pred - y)))
    print(f"mean abs error after {epochs} epochs: {err:.4f}")
    return err


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=30)
    a = p.parse_args()
    run(epochs=a.epochs)


if __name__ == "__main__":
    main()
