"""Text classification example — news20-style (reference
pyzoo/zoo/examples/textclassification/text_classification.py: TextSet
pipeline -> TextClassifier(cnn|lstm|gru) -> fit/evaluate).

With --data-dir, expects news20 layout: one subfolder per class, one .txt
document per file.  Without, a synthetic corpus (class-specific vocabulary)
checks the full pipeline end-to-end.

Usage:
    python examples/textclassification/train.py --encoder cnn --epochs 10
"""

import argparse
import glob
import os

import numpy as np


def load_corpus(data_dir=None, n_classes=4, n_docs=400, seed=0):
    if data_dir:
        texts, labels, names = [], [], sorted(os.listdir(data_dir))
        for li, cls in enumerate(names):
            for p in glob.glob(os.path.join(data_dir, cls, "*")):
                with open(p, errors="ignore") as f:
                    texts.append(f.read())
                labels.append(li)
        return texts, labels, len(names)
    # synthetic: each class favors its own token family
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    for i in range(n_docs):
        c = int(rng.integers(n_classes))
        own = [f"w{c}_{int(rng.integers(30))}" for _ in range(20)]
        common = [f"c{int(rng.integers(50))}" for _ in range(10)]
        words = own + common
        rng.shuffle(words)
        texts.append(" ".join(words))
        labels.append(c)
    return texts, labels, n_classes


def run(data_dir=None, encoder="cnn", sequence_length=100, epochs=10,
        batch_size=32, token_length=64):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.feature.text import TextSet
    from analytics_zoo_tpu.models.textclassification import TextClassifier

    init_zoo_context("text classification")
    texts, labels, n_classes = load_corpus(data_dir)
    n_train = int(0.8 * len(texts))

    train = TextSet.from_texts(texts[:n_train], labels[:n_train]) \
        .tokenize().normalize() \
        .word2idx(remove_topn=0, max_words_num=20000) \
        .shape_sequence(sequence_length)
    test = TextSet.from_texts(texts[n_train:], labels[n_train:]) \
        .tokenize().normalize() \
        .word2idx(existing_map=train.get_word_index()) \
        .shape_sequence(sequence_length)

    model = TextClassifier(
        class_num=n_classes, token_length=token_length,
        sequence_length=sequence_length, encoder=encoder,
        vocab_size=len(train.get_word_index()) + 1)
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(train.to_feature_set(), batch_size=batch_size,
              nb_epoch=epochs)
    results = model.evaluate(test.to_feature_set(), batch_size=batch_size)
    return results


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default=None,
                    help="news20-style folder tree (default: synthetic)")
    ap.add_argument("--encoder", default="cnn",
                    choices=("cnn", "lstm", "gru"))
    ap.add_argument("--sequence-length", type=int, default=100)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()
    results = run(args.data_dir, args.encoder, args.sequence_length,
                  args.epochs, args.batch_size)
    print("test:", {k: round(v, 4) for k, v in results.items()})


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
