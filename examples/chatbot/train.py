"""Chatbot-style seq2seq example (reference
zoo/.../examples/chatbot: RNNEncoder + Bridge + RNNDecoder trained with
teacher forcing, greedy generation at inference).

With --pairs, expects tab-separated ``question<TAB>answer`` lines.
Without, a synthetic phrase-response corpus (each question token family
maps to a deterministic answer family), so the example always runs and
visibly learns.

Usage:
    python examples/chatbot/train.py --epochs 20
"""

import argparse

import numpy as np

PAD, START = 0, 1
_BASE = 2


def synth_pairs(n=512, n_patterns=6, q_len=6, a_len=6, seed=0):
    """question = pattern tokens + noise; answer = mapped pattern tokens."""
    rng = np.random.default_rng(seed)
    vocab = _BASE + 2 * n_patterns + 10
    q = np.zeros((n, q_len), np.int64)
    a_in = np.zeros((n, a_len), np.int64)
    a_out = np.zeros((n, a_len), np.int64)
    for i in range(n):
        p = int(rng.integers(n_patterns))
        q_tok = _BASE + p
        a_tok = _BASE + n_patterns + p
        q[i] = [q_tok] * 3 + list(
            rng.integers(_BASE + 2 * n_patterns, vocab, size=q_len - 3))
        ans = [a_tok] * a_len
        a_out[i] = ans
        a_in[i] = [START] + ans[:-1]
    return q, a_in, a_out, vocab


def load_pairs(path, q_len=10, a_len=10):
    from analytics_zoo_tpu.feature.text import TextSet

    qs, ans = [], []
    with open(path) as f:
        for line in f:
            if "\t" in line:
                q_txt, a_txt = line.rstrip("\n").split("\t", 1)
                qs.append(q_txt)
                ans.append(a_txt)
    q_set = TextSet.from_texts(qs).tokenize().normalize().word2idx() \
        .shape_sequence(q_len)
    a_set = TextSet.from_texts(ans).tokenize().normalize().word2idx(
        existing_map=q_set.get_word_index()).shape_sequence(a_len)
    vocab = len(q_set.get_word_index()) + 2
    q = np.stack([f.indices for f in q_set.features]) + 1  # 0=pad, 1=start
    a = np.stack([f.indices for f in a_set.features]) + 1
    a_in = np.concatenate([np.full((len(a), 1), START), a[:, :-1]], 1)
    return q, a_in, a, vocab + 1


def run(pairs=None, epochs=20, batch_size=64):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models import Seq2seq
    from analytics_zoo_tpu.pipeline.api.keras import Input, Model
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    init_zoo_context("chatbot seq2seq")
    if pairs:
        q, a_in, a_out, vocab = load_pairs(pairs)
    else:
        q, a_in, a_out, vocab = synth_pairs()
    s2s = Seq2seq(vocab_size=vocab, embed_dim=32, hidden_sizes=(64,))
    e_in = Input(shape=(q.shape[1],), name="enc_in")
    d_in = Input(shape=(a_in.shape[1],), name="dec_in")
    net = Model([e_in, d_in], s2s([e_in, d_in]))
    net.compile(optimizer=Adam(lr=0.01),
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    net.fit([q, a_in], a_out, batch_size=batch_size, nb_epoch=epochs)
    res = net.evaluate([q, a_in], a_out, batch_size=batch_size)
    replies = s2s.infer(net.params[s2s.name], q[:4], start_sign=START,
                        max_len=a_out.shape[1])
    return res, np.asarray(replies), a_out[:4]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pairs", default=None,
                    help="tab-separated question/answer file")
    ap.add_argument("--epochs", type=int, default=20)
    args = ap.parse_args()
    res, replies, expect = run(args.pairs, args.epochs)
    print("teacher-forced:", {k: round(v, 4) for k, v in res.items()})
    for r, e in zip(replies, expect):
        print("generated:", r.tolist(), " expected:", e.tolist())


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
