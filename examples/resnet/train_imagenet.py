"""ResNet-50 ImageNet training — the flagship benchmark config.

Reference: zoo/.../examples/resnet/TrainImageNet.scala:36-120 (warmup +
epoch-decay SGD) and the vnni Perf harness
(examples/vnni/bigdl/Perf.scala:53-66) that prints images/sec.

`bench.py` at the repo root invokes :func:`run` — this example IS the
benchmark.  With --data-dir it trains on ``.npz`` image shards (uint8 HWC
images + int labels); without, synthetic data measures training throughput.

The input pipeline is TPU-shaped: the host ships **uint8** images (4× less
host→device traffic than f32) and normalization runs on-device inside the
compiled step (``FeatureSet.transform_on_device``).  ``run`` measures and
reports separately:

- ``pure_step``: the jitted train step on a device-resident batch — the
  framework's compute number;
- ``e2e``: end-to-end ``fit`` including host batch assembly + H2D infeed;
- ``infeed_fraction``: (e2e − pure) / e2e — how much of the wall clock the
  infeed fails to hide behind compute;
- ``compiles_timed``: XLA compilations observed during the timed epoch
  (must be 0 — anything else means per-step retracing).

Usage:
    python examples/resnet/train_imagenet.py --steps 30 --batch-size 256
"""

import argparse
import logging
import time

import numpy as np

# ImageNet channel stats (uint8 scale), applied on device.
_MEAN = np.array([123.675, 116.28, 103.53], np.float32)
_STD = np.array([58.395, 57.12, 57.375], np.float32)


class _CompileCounter(logging.Handler):
    """Counts XLA compile events (jax_log_compiles messages)."""

    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        # jax_log_compiles emits both "Compiling <fn>..." (pxla) and
        # "Finished tracing + compilation..." (dispatch) per compile; count
        # only the former so the magnitude is exact.
        if record.getMessage().startswith("Compiling"):
            self.count += 1


def _normalize(batch):
    import jax.numpy as jnp

    x = batch["x"].astype(jnp.float32)
    x = (x - jnp.asarray(_MEAN)) / jnp.asarray(_STD)
    return {**batch, "x": x}


def run(image_size=224, per_chip_batch=256, steps=30, classes=1000,
        depth=50, data_dir=None, warmup_batches=2):
    """Train ResNet-`depth` for `steps` steps; returns a result dict."""
    import jax

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.feature.dataset import FeatureSet
    from analytics_zoo_tpu.models.resnet import ResNet

    ctx = init_zoo_context("resnet imagenet")
    model = ResNet.image_net(depth, classes=classes,
                             input_shape=(image_size, image_size, 3))
    model.compile(
        optimizer=ResNet.imagenet_optimizer(batch_size=per_chip_batch,
                                            steps_per_epoch=5004),
        loss="sparse_categorical_crossentropy",
    )
    batch = per_chip_batch * max(ctx.data_parallel_size, 1)

    if data_dir:
        from analytics_zoo_tpu.feature.imagenet import imagenet_feature_set

        train_set = imagenet_feature_set(data_dir, image_size)
    else:
        n = batch * steps
        rng = np.random.default_rng(0)
        x = rng.integers(0, 256, size=(n, image_size, image_size, 3),
                         dtype=np.uint8)
        y = rng.integers(0, classes, size=(n,)).astype(np.int32)
        train_set = FeatureSet.of(x, y)
    train_set.transform_on_device(_normalize)
    n = train_set.num_samples // batch * batch
    steps_run = n // batch
    if steps_run < 1:
        raise ValueError(
            f"dataset has {train_set.num_samples} samples — fewer than one "
            f"global batch ({batch}); reduce --batch-size or add data")

    # Bounded warmup (compile + first dispatches), never a full --data-dir
    # epoch: a tiny synthetic set with the same shapes compiles the same
    # XLA program.
    wrng = np.random.default_rng(1)
    warm = FeatureSet.of(
        wrng.integers(0, 256, size=(batch * warmup_batches, image_size,
                                    image_size, 3), dtype=np.uint8),
        wrng.integers(0, classes,
                      size=(batch * warmup_batches,)).astype(np.int32),
    ).transform_on_device(_normalize)
    model.fit(warm, batch_size=batch, nb_epoch=1)

    # Timed end-to-end epoch, counting any (unexpected) recompiles.
    jax.config.update("jax_log_compiles", True)
    counter = _CompileCounter()
    logging.getLogger("jax").addHandler(counter)
    try:
        t0 = time.perf_counter()
        model.fit(train_set, batch_size=batch, nb_epoch=1)
        e2e_dt = time.perf_counter() - t0
    finally:
        jax.config.update("jax_log_compiles", False)
        logging.getLogger("jax").removeHandler(counter)

    # Pure-device step: same compiled fn on a device-resident batch
    # (fresh buffers inside the hook, so donation can't touch live state).
    # Multi-host: this host materializes only its rows, like fit() does.
    ps = ((jax.process_index(), jax.process_count())
          if jax.process_count() > 1 else None)
    first = next(iter(train_set.batches(batch, shuffle=False, epoch=0,
                                        process_shard=ps)))
    pure_dt = model._estimator.measure_pure_step(
        first, n_steps=min(20, steps_run),
        device_transform=train_set.device_transform)

    e2e_ips = n / e2e_dt
    pure_ips = batch / pure_dt
    return {
        "ctx": ctx,
        "e2e_ips": e2e_ips,
        "pure_ips": pure_ips,
        "pure_step_ms": pure_dt * 1e3,
        "infeed_fraction": max(0.0, 1.0 - (pure_dt * steps_run) / e2e_dt),
        "compiles_timed": counter.count,
        "steps_timed": steps_run,
        "batch": batch,
        "image_size": image_size,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default=None,
                    help="dir of .npz shards (default: synthetic)")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--batch-size", type=int, default=256,
                    help="per-chip batch size")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--depth", type=int, default=50)
    args = ap.parse_args()

    r = run(image_size=args.image_size, per_chip_batch=args.batch_size,
            steps=args.steps, depth=args.depth, data_dir=args.data_dir)
    ctx = r["ctx"]
    dp = max(ctx.data_parallel_size, 1)
    print(f"e2e: {r['e2e_ips']:.1f} img/s ({r['e2e_ips'] / dp:.1f}/chip) | "
          f"pure step: {r['pure_ips']:.1f} img/s "
          f"({r['pure_step_ms']:.1f} ms) | "
          f"infeed fraction: {r['infeed_fraction']:.2f} | "
          f"compiles during timing: {r['compiles_timed']} | "
          f"{ctx.num_devices} {ctx.platform} device(s)")


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
