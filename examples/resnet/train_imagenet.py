"""ResNet-50 ImageNet training — the flagship benchmark config.

Reference: zoo/.../examples/resnet/TrainImageNet.scala:36-120 (warmup +
epoch-decay SGD) and the vnni Perf harness
(examples/vnni/bigdl/Perf.scala:53-66) that prints images/sec.

`bench.py` at the repo root invokes :func:`run` — this example IS the
benchmark.  With --data-dir it trains on an ImageNet-layout folder tree
(shards built via FeatureSet.from_shards); without, synthetic data measures
pure training throughput.

Usage:
    python examples/resnet/train_imagenet.py --steps 30 --batch-size 256
"""

import argparse
import time

import numpy as np


def run(image_size=224, per_chip_batch=256, steps=30, classes=1000,
        depth=50, data_dir=None, warmup_batches=2):
    """Train ResNet-`depth` for `steps` steps; returns (img/s, ctx)."""
    from analytics_zoo_tpu import get_zoo_context, init_zoo_context
    from analytics_zoo_tpu.models.resnet import ResNet

    ctx = init_zoo_context("resnet imagenet")
    model = ResNet.image_net(depth, classes=classes,
                             input_shape=(image_size, image_size, 3))
    model.compile(
        optimizer=ResNet.imagenet_optimizer(batch_size=per_chip_batch,
                                            steps_per_epoch=5004),
        loss="sparse_categorical_crossentropy",
    )
    batch = per_chip_batch * max(ctx.data_parallel_size, 1)
    if data_dir:
        import glob

        from analytics_zoo_tpu.feature.dataset import FeatureSet
        train_set = FeatureSet.from_shards(
            sorted(glob.glob(f"{data_dir}/*.npz")))
        n = train_set.num_samples // batch * batch
        model.fit(train_set, batch_size=batch, nb_epoch=1)  # warm + compile
        t0 = time.perf_counter()
        model.fit(train_set, batch_size=batch, nb_epoch=1)
        return n / (time.perf_counter() - t0), ctx

    n = batch * steps
    x = np.random.default_rng(0).normal(
        size=(n, image_size, image_size, 3)).astype(np.float32)
    y = np.random.default_rng(1).integers(
        0, classes, size=(n,)).astype(np.int32)
    # warmup (includes XLA compile)
    model.fit(x[:batch * warmup_batches], y[:batch * warmup_batches],
              batch_size=batch, nb_epoch=1)
    t0 = time.perf_counter()
    model.fit(x, y, batch_size=batch, nb_epoch=1)
    dt = time.perf_counter() - t0
    return n / dt, ctx


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default=None,
                    help="dir of .npz shards (default: synthetic)")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--batch-size", type=int, default=256,
                    help="per-chip batch size")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--depth", type=int, default=50)
    args = ap.parse_args()

    ips, ctx = run(image_size=args.image_size,
                   per_chip_batch=args.batch_size, steps=args.steps,
                   depth=args.depth, data_dir=args.data_dir)
    per_chip = ips / max(ctx.data_parallel_size, 1)
    print(f"throughput: {ips:.1f} img/s total, {per_chip:.1f} img/s/chip "
          f"({ctx.num_devices} {ctx.platform} device(s))")


if __name__ == "__main__":
    main()
