"""ResNet on CIFAR-10.

Reference: zoo/.../examples/resnet/TrainCIFAR10.scala (warmup + step-decay
LR schedule) and resnet/TrainImageNet.scala:36-120.

Reads the CIFAR-10 python pickle batches from --data-dir if present
(cifar-10-batches-py/); otherwise a procedural 10-class stand-in.

Usage:
    python examples/resnet/train_cifar10.py --depth 20 --epochs 10
    python examples/resnet/train_cifar10.py --data-dir /data/cifar10
"""

import argparse
import os
import pickle

import numpy as np


def load_cifar10(data_dir):
    d = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(d):
        d = data_dir

    def load_batch(name):
        with open(os.path.join(d, name), "rb") as f:
            blob = pickle.load(f, encoding="bytes")
        x = blob[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x, np.asarray(blob[b"labels"], np.int32)

    parts = [load_batch(f"data_batch_{i}") for i in range(1, 6)]
    xtr = np.concatenate([p[0] for p in parts])
    ytr = np.concatenate([p[1] for p in parts])
    xte, yte = load_batch("test_batch")
    return (xtr, ytr), (xte, yte)


def synthetic_cifar(n_train=4096, n_test=1024, seed=0):
    """Class = dominant color patch position/hue; learnable by a small
    ResNet within a few epochs."""
    rng = np.random.default_rng(seed)

    def make(n):
        y = rng.integers(0, 10, n).astype(np.int32)
        x = rng.normal(64, 24, (n, 32, 32, 3)).clip(0, 255)
        for i, c in enumerate(y):
            r, col = divmod(int(c), 5)
            x[i, 4 + r * 14:16 + r * 14, 2 + col * 6:8 + col * 6, c % 3] = 240
        return x.astype(np.uint8), y

    return make(n_train), make(n_test)


def run(data_dir=None, depth=20, batch_size=128, epochs=10, lr=0.1,
        n_train=4096, steps=None, per_chip_batch=None):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.resnet import ResNet
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
        SGD,
        warmup_epoch_decay,
    )

    ctx = init_zoo_context("resnet cifar10 example")
    if per_chip_batch is not None:
        batch_size = per_chip_batch * max(ctx.data_parallel_size, 1)
    if data_dir:
        (xtr, ytr), (xte, yte) = load_cifar10(data_dir)
    else:
        (xtr, ytr), (xte, yte) = synthetic_cifar(n_train)
    if steps is not None:
        n = max(batch_size * steps, batch_size)
        xtr, ytr = xtr[:n], ytr[:n]
        xte, yte = xte[:n], yte[:n]
        epochs = 1

    mean = np.asarray([125.3, 123.0, 113.9], np.float32)
    std = np.asarray([63.0, 62.1, 66.7], np.float32)

    def prep(x):
        return (x.astype(np.float32) - mean) / std

    spe = max(len(xtr) // batch_size, 1)
    model = ResNet.cifar(depth=depth)
    # TrainImageNet.scala LR recipe: linear warmup then epoch-step decay.
    schedule = warmup_epoch_decay(
        warmup_steps=spe, steps_per_epoch=spe,
        boundaries_epochs=(max(epochs // 2, 1), max(3 * epochs // 4, 2)),
        decay=0.1,
    )
    model.compile(
        optimizer=SGD(lr=lr, momentum=0.9, weight_decay=1e-4,
                      schedule=schedule),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
    )
    model.fit(prep(xtr), ytr.astype(np.int32), batch_size=batch_size,
              nb_epoch=epochs)
    return model.evaluate(prep(xte), yte.astype(np.int32),
                          batch_size=batch_size)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--depth", type=int, default=20,
                    help="resnet depth (20/32/44/56 basic-block)")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--n-train", type=int, default=4096)
    args = ap.parse_args()
    results = run(args.data_dir, args.depth, args.batch_size, args.epochs,
                  args.lr, args.n_train)
    print({k: round(float(v), 4) for k, v in results.items()})


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
