"""tfpark KerasModel trained from a DATASET (reference
pyzoo/zoo/examples/tensorflow/tfpark/keras/keras_dataset.py: mnist via
TFDataset.from_rdd feeding a tf.keras model; its sibling
keras_ndarray.py feeds ndarrays — see examples/tfpark/keras_ndarray.py).

Here the dataset role is played by :class:`FeatureSet` — the framework's
TFDataset equivalent — streaming batches (with exact-resume iterator
state) into the jit-compiled train step.

Usage: python examples/tfpark/keras_dataset.py [--epochs 12]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def digits_data():
    from sklearn.datasets import load_digits

    d = load_digits()
    x = (d.images[..., None] / 16.0).astype(np.float32)  # (N, 8, 8, 1)
    y = d.target.astype(np.int32)
    n = (int(len(x) * 0.85) // 64) * 64
    return (x[:n], y[:n]), (x[n:], y[n:])


def run(epochs=12, batch_size=64):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.feature.dataset import FeatureSet
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D, Dense, Flatten,
    )
    from analytics_zoo_tpu.tfpark import KerasModel

    init_zoo_context("tfpark keras_dataset", seed=0)
    (xt, yt), (xv, yv) = digits_data()
    train_set = FeatureSet.of(xt, yt)   # the TFDataset.from_rdd role

    net = Sequential()
    net.add(Convolution2D(8, 3, 3, activation="relu",
                          input_shape=(8, 8, 1)))
    net.add(Flatten())
    net.add(Dense(32, activation="relu"))
    net.add(Dense(10, activation="softmax"))
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    net.compile(optimizer=Adam(lr=0.01),
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])

    km = KerasModel(net)
    km.fit(train_set, batch_size=batch_size, epochs=epochs)
    metrics = km.evaluate(xv, yv, batch_size=batch_size)
    preds = km.predict(xv[:16], batch_size=16)
    print("val metrics:", {k: round(float(v), 4) for k, v in
                           metrics.items()})
    print("pred shape:", np.asarray(preds).shape)
    return metrics


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=12)
    a = ap.parse_args()
    m = run(epochs=a.epochs)
    assert m["accuracy"] > 0.9, m


if __name__ == "__main__":
    main()
