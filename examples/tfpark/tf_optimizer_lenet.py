"""Train-then-evaluate LeNet with checkpointing (reference
pyzoo/zoo/examples/tensorflow/tfpark/tf_optimizer/{train_lenet.py,
evaluate_lenet.py}: TFOptimizer drives a tf graph, checkpoints to
model_dir, and a separate evaluate run restores the checkpoint).

Two phases, mirroring the reference's two scripts:
  train:    fit LeNet on digits, checkpointing every epoch;
  evaluate: a FRESH process/model restores the latest checkpoint via the
            estimator resume path and evaluates without training.

Usage: python examples/tfpark/tf_optimizer_lenet.py [--epochs 10]
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def digits_data():
    from sklearn.datasets import load_digits

    d = load_digits()
    x = (d.images[..., None] / 16.0).astype(np.float32)
    y = d.target.astype(np.int32)
    n = (int(len(x) * 0.85) // 64) * 64
    return (x[:n], y[:n]), (x[n:], y[n:])


def build_lenet():
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D, Dense, Flatten, MaxPooling2D,
    )

    m = Sequential()
    m.add(Convolution2D(6, 3, 3, activation="relu", border_mode="same",
                        input_shape=(8, 8, 1)))
    m.add(MaxPooling2D((2, 2)))
    m.add(Convolution2D(16, 3, 3, activation="relu"))
    m.add(Flatten())
    m.add(Dense(32, activation="relu"))
    m.add(Dense(10, activation="softmax"))
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    m.compile(optimizer=Adam(lr=0.01),
              loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    return m


def train(model_dir, epochs=10, batch_size=64):
    """The train_lenet.py role: fit + checkpoint to model_dir."""
    from analytics_zoo_tpu import init_zoo_context

    init_zoo_context("tf_optimizer train_lenet", seed=0)
    (xt, yt), _ = digits_data()
    m = build_lenet()
    m.set_checkpoint(model_dir)
    m.fit(xt, yt, batch_size=batch_size, nb_epoch=epochs)
    return m


def evaluate(model_dir, batch_size=64):
    """The evaluate_lenet.py role: fresh model, restore latest
    checkpoint, evaluate — no training."""
    from analytics_zoo_tpu import init_zoo_context

    init_zoo_context("tf_optimizer evaluate_lenet", seed=0)
    _, (xv, yv) = digits_data()
    m = build_lenet()
    m.load_checkpoint(model_dir)
    metrics = m.evaluate(xv, yv, batch_size=batch_size)
    print("restored-checkpoint val:",
          {k: round(float(v), 4) for k, v in metrics.items()})
    return metrics


def run(epochs=10, model_dir=None):
    model_dir = model_dir or tempfile.mkdtemp()
    train(model_dir, epochs=epochs)
    return evaluate(model_dir)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--model-dir", default=None)
    a = ap.parse_args()
    m = run(epochs=a.epochs, model_dir=a.model_dir)
    assert m["accuracy"] > 0.9, m


if __name__ == "__main__":
    main()
