"""tfpark example — model_fn estimator + KerasModel (reference
pyzoo/zoo/examples/tensorflow/tfpark/{estimator_dataset.py,
keras_dataset.py}: tf.estimator-style training driven by the zoo
runtime; here the model_fn builds symbolic zoo layers and the whole
train step compiles to one XLA program).

Usage:
    python examples/tfpark/estimator_example.py --steps 300
"""

import argparse

import numpy as np


def blobs(n=512, d=8, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, classes, n)
    centers = rng.normal(size=(classes, d)) * 3
    x = centers[y] + rng.normal(size=(n, d)) * 0.4
    return x.astype(np.float32), y.astype(np.int32)


def run(steps=300, batch_size=32):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.tfpark import (
        KerasModel,
        TFEstimator,
        TFEstimatorSpec,
        sparse_ce,
    )

    init_zoo_context("tfpark example")
    x, y = blobs()
    n_train = int(0.8 * len(x))

    # 1. tf.estimator-style model_fn (TFEstimator)
    def model_fn(features, labels, mode, params):
        h = Dense(24, activation="relu")(features)
        probs = Dense(3, activation="softmax")(h)
        if mode == "predict" or labels is None:
            return TFEstimatorSpec(mode, predictions=probs)
        return TFEstimatorSpec(mode, predictions=probs,
                               loss=sparse_ce(probs, labels))

    est = TFEstimator(model_fn, optimizer="adam")
    est.train(lambda: (x[:n_train], y[:n_train]), steps=steps,
              batch_size=batch_size)
    est_metrics = est.evaluate(lambda: (x[n_train:], y[n_train:]),
                               ["accuracy"])

    # 2. tf.keras-style compiled model (tfpark KerasModel)
    net = Sequential()
    net.add(Dense(24, activation="relu", input_shape=(8,)))
    net.add(Dense(3, activation="softmax"))
    net.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    km = KerasModel(net)
    km.fit(x[:n_train], y[:n_train], batch_size=batch_size, epochs=8)
    km_metrics = km.evaluate(x[n_train:], y[n_train:],
                             batch_size=batch_size)
    return est_metrics, km_metrics


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=300)
    args = ap.parse_args()
    est_m, km_m = run(args.steps)
    print("TFEstimator:", {k: round(float(v), 4) for k, v in est_m.items()})
    print("KerasModel: ", {k: round(float(v), 4) for k, v in km_m.items()})


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
