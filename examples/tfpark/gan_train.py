"""GANEstimator training + sampling (reference
pyzoo/zoo/examples/tensorflow/tfpark/gan/{gan_train.py,gan_eval.py}:
train a GAN with TFGAN-style losses, then generate from the checkpoint).

The data is a shifted 2-D Gaussian so CI can assert the generator's
distribution moved; swap in image batches for a DCGAN.

Usage: python examples/tfpark/gan_train.py [--steps 600]
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def run(steps=600, model_dir=None):
    import jax.numpy as jnp

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.tfpark.gan import GANEstimator

    init_zoo_context("tfpark gan", seed=0)
    rng = np.random.default_rng(0)
    n = 512
    noise = rng.normal(size=(n, 4)).astype(np.float32)
    real = (3.0 + 0.5 * rng.normal(size=(n, 2))).astype(np.float32)

    def generator_fn(z):
        h = Dense(16, activation="relu")(z)
        return Dense(2)(h)

    def discriminator_fn(x):
        h = Dense(16, activation="relu")(x)
        return Dense(1)(h)

    def g_loss(fake_logits):  # non-saturating generator loss
        return jnp.mean(jnp.logaddexp(0.0, -fake_logits))

    def d_loss(real_logits, fake_logits):
        return jnp.mean(jnp.logaddexp(0.0, -real_logits)) + \
            jnp.mean(jnp.logaddexp(0.0, fake_logits))

    est = GANEstimator(
        generator_fn, discriminator_fn, g_loss, d_loss,
        generator_optimizer="adam", discriminator_optimizer="adam",
        model_dir=model_dir or tempfile.mkdtemp())
    est.train((noise, real), steps=steps, batch_size=64)

    # gan_eval role: sample the trained generator
    samples = est.generate(noise[:256])
    mean = float(np.asarray(samples).mean())
    print(f"generator sample mean after {steps} steps: {mean:.2f} "
          f"(real mean 3.0)")
    return mean


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=600)
    a = p.parse_args()
    run(steps=a.steps)


if __name__ == "__main__":
    main()
