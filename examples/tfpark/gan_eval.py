"""Sample a trained GAN from its checkpoint directory (reference
pyzoo/zoo/examples/tensorflow/tfpark/gan/gan_eval.py: rebuild the
generator variable scope, restore from the train run's checkpoint, and
generate a grid).

A FRESH ``GANEstimator`` pointed at the same ``model_dir`` lazily
restores the generator the first time ``generate`` runs — no training in
this script; run gan_train first (or let this script invoke it).

Usage: python examples/tfpark/gan_eval.py [--model-dir DIR]
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def run(model_dir=None, train_steps=400):
    import jax.numpy as jnp

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.tfpark.gan import GANEstimator

    if model_dir is None:
        # no checkpoint supplied: produce one the way gan_train.py does
        from examples.tfpark.gan_train import run as train_run

        model_dir = tempfile.mkdtemp()
        train_run(steps=train_steps, model_dir=model_dir)

    init_zoo_context("tfpark gan eval", seed=0)

    # generator/discriminator architecture must match the training run
    # (the reference rebuilds the same variable scope before restoring)
    def generator_fn(z):
        h = Dense(16, activation="relu")(z)
        return Dense(2)(h)

    def discriminator_fn(x):
        h = Dense(16, activation="relu")(x)
        return Dense(1)(h)

    def g_loss(fake_logits):
        return jnp.mean(jnp.logaddexp(0.0, -fake_logits))

    def d_loss(real_logits, fake_logits):
        return jnp.mean(jnp.logaddexp(0.0, -real_logits)) + \
            jnp.mean(jnp.logaddexp(0.0, fake_logits))

    est = GANEstimator(generator_fn, discriminator_fn, g_loss, d_loss,
                       generator_optimizer="adam",
                       discriminator_optimizer="adam", model_dir=model_dir)
    rng = np.random.default_rng(1)
    noise = rng.normal(size=(512, 4)).astype(np.float32)
    samples = np.asarray(est.generate(noise))
    mean = float(samples.mean())
    spread = float(samples.std())
    print(f"restored generator: sample mean {mean:.2f} (real data mean "
          f"3.0), std {spread:.2f}")
    return mean, spread


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model-dir", default=None,
                    help="gan_train model_dir to restore; trains one "
                         "on the fly if omitted")
    ap.add_argument("--train-steps", type=int, default=400)
    a = ap.parse_args()
    run(model_dir=a.model_dir, train_steps=a.train_steps)


if __name__ == "__main__":
    main()
