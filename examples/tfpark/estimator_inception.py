"""TFEstimator with an inception-style conv model_fn (reference
pyzoo/zoo/examples/tensorflow/tfpark/estimator/estimator_inception.py:
slim inception_v1 inside a tf.estimator model_fn, trained on an image
folder via TFDataset).

The model_fn builds a miniature inception block — parallel 1x1 / 3x3 /
pooled branches concatenated, the reference architecture's signature —
from symbolic zoo layers; the whole train step compiles to one XLA
program.  Images are a learnable synthetic set (class = blob quadrant).

Usage: python examples/tfpark/estimator_inception.py [--steps 120]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def make_images(n=512, size=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 0.25, size=(n, size, size, 3)).astype(np.float32)
    y = rng.integers(classes, size=n).astype(np.int32)
    h = size // 2
    for i, c in enumerate(y):
        r, col = divmod(int(c), 2)
        x[i, r * h:(r + 1) * h, col * h:(col + 1) * h, :] += 1.0
    return x, y


def run(steps=120, batch_size=64):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        AveragePooling2D, Convolution2D, Dense, Flatten,
    )
    from analytics_zoo_tpu.pipeline.api.keras.topology import merge
    from analytics_zoo_tpu.tfpark import (
        TFEstimator,
        TFEstimatorSpec,
        sparse_ce,
    )

    init_zoo_context("tfpark estimator_inception", seed=0)
    x, y = make_images()
    n_train = (int(0.85 * len(x)) // batch_size) * batch_size

    def model_fn(features, labels, mode, params):
        # miniature inception block: 1x1, 3x3, and avg-pool+1x1 branches
        b1 = Convolution2D(8, 1, 1, activation="relu")(features)
        b3 = Convolution2D(8, 3, 3, activation="relu",
                           border_mode="same")(features)
        bp = AveragePooling2D((2, 2), strides=(1, 1),
                              border_mode="same")(features)
        bp = Convolution2D(8, 1, 1, activation="relu")(bp)
        block = merge([b1, b3, bp], mode="concat", concat_axis=-1)
        h = Flatten()(block)
        probs = Dense(4, activation="softmax")(h)
        if mode == "predict" or labels is None:
            return TFEstimatorSpec(mode, predictions=probs)
        return TFEstimatorSpec(mode, predictions=probs,
                               loss=sparse_ce(probs, labels))

    est = TFEstimator(model_fn, optimizer="adam")
    est.train(lambda: (x[:n_train], y[:n_train]), steps=steps,
              batch_size=batch_size)
    metrics = est.evaluate(lambda: (x[n_train:], y[n_train:]),
                           ["accuracy"])
    print("val:", {k: round(float(v), 4) for k, v in metrics.items()})
    return metrics


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=120)
    a = ap.parse_args()
    m = run(steps=a.steps)
    assert m["accuracy"] > 0.8, m


if __name__ == "__main__":
    main()
