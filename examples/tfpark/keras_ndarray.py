"""tfpark KerasModel on in-memory ndarrays (reference
pyzoo/zoo/examples/tensorflow/tfpark/keras/keras_ndarray.py: wrap a keras
model in tfpark.KerasModel, fit/evaluate/predict on numpy arrays).

Usage: python examples/tfpark/keras_ndarray.py [--epochs 8]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def run(epochs=20):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Dropout
    from analytics_zoo_tpu.tfpark import KerasModel

    init_zoo_context("tfpark keras_ndarray", seed=0)
    from sklearn.datasets import load_digits

    d = load_digits()
    x = (d.images.reshape(-1, 64) / 16.0).astype(np.float32)
    y = d.target.astype(np.int32)
    perm = np.random.default_rng(0).permutation(len(x))
    x, y = x[perm], y[perm]
    n = (len(x) // 64) * 64
    x, y = x[:n], y[:n]
    n_train = int(n * 0.8) // 64 * 64

    net = Sequential()
    net.add(Dense(64, activation="relu", input_shape=(64,)))
    net.add(Dropout(0.2))
    net.add(Dense(10, activation="softmax"))
    net.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])

    model = KerasModel(net)
    model.fit(x[:n_train], y[:n_train], batch_size=64, epochs=epochs)
    metrics = model.evaluate(x[n_train:], y[n_train:], batch_per_thread=64)
    preds = model.predict(x[n_train:], batch_per_thread=64)
    classes = model.predict_classes(x[n_train:])
    acc = float((classes == y[n_train:]).mean())
    print(f"eval: {metrics} | predict {preds.shape} | acc {acc:.3f}")
    return acc


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=20)
    a = p.parse_args()
    run(epochs=a.epochs)


if __name__ == "__main__":
    main()
