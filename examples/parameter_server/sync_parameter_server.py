"""Synchronous parameter server on the actor runtime.

Mirror of the reference example
pyzoo/zoo/examples/ray/parameter_server/sync_parameter_server.py (a
``@ray.remote`` ParameterServer + Worker pair on RayOnSpark), rebuilt on
``analytics_zoo_tpu.parallel.actors``: the PS actor owns the flat weight
vector and applies averaged gradients; worker actors hold data shards and
compute gradients at the current weights.  The model is a pure-numpy
softmax regression on sklearn digits so actor processes stay jax-free
(fork safety) — the point of this example is the DISTRIBUTION pattern,
not the math.

Usage: python examples/parameter_server/sync_parameter_server.py
       [--num-workers 4] [--iterations 40]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from analytics_zoo_tpu.parallel.actors import (
    ActorContext,
    get,
    remote,
)

DIM, CLASSES = 64, 10


def softmax_grads(w_flat, x, y):
    """loss + gradient of softmax regression, flat-vector weights."""
    w = w_flat[:DIM * CLASSES].reshape(DIM, CLASSES)
    b = w_flat[DIM * CLASSES:]
    logits = x @ w + b
    logits -= logits.max(1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(1, keepdims=True)
    n = len(x)
    loss = -np.log(p[np.arange(n), y] + 1e-12).mean()
    dlogits = p
    dlogits[np.arange(n), y] -= 1.0
    dlogits /= n
    gw = x.T @ dlogits
    gb = dlogits.sum(0)
    return loss, np.concatenate([gw.reshape(-1), gb])


@remote
class ParameterServer:
    """Owns the weights; applies averaged worker gradients (reference
    sync_parameter_server.py ParameterServer.apply_gradients)."""

    def __init__(self, learning_rate=0.5):
        self.lr = learning_rate
        rng = np.random.default_rng(0)
        self.w = (rng.normal(0, 0.01, DIM * CLASSES + CLASSES)
                  .astype(np.float64))

    def apply_gradients(self, *gradients):
        self.w -= self.lr * np.mean(gradients, axis=0)
        return self.w

    def get_weights(self):
        return self.w


@remote
class Worker:
    """Holds a data shard; computes gradients at given weights (reference
    Worker.compute_gradients)."""

    def __init__(self, worker_index, num_workers, batch_size=128):
        from sklearn.datasets import load_digits

        d = load_digits()
        x = (d.images.reshape(-1, DIM) / 16.0).astype(np.float64)
        y = d.target.astype(np.int64)
        self.x = x[worker_index::num_workers]
        self.y = y[worker_index::num_workers]
        self.batch = batch_size
        self.rng = np.random.default_rng(worker_index)
        self.last_loss = None

    def compute_gradients(self, weights):
        idx = self.rng.integers(0, len(self.x), self.batch)
        loss, g = softmax_grads(weights, self.x[idx], self.y[idx])
        self.last_loss = float(loss)
        return g

    def loss_on_shard(self, weights):
        loss, _ = softmax_grads(weights, self.x, self.y)
        return float(loss)


def run(num_workers=4, iterations=40, lr=0.5):
    ctx = ActorContext.init()
    ps = ParameterServer.remote(lr)
    workers = [Worker.remote(i, num_workers) for i in range(num_workers)]

    weights = ps.get_weights.remote().get()
    loss0 = float(np.mean(get(
        [w.loss_on_shard.remote(weights) for w in workers])))
    for _ in range(iterations):
        grads = get([w.compute_gradients.remote(weights) for w in workers])
        weights = ps.apply_gradients.remote(*grads).get()
    loss1 = float(np.mean(get(
        [w.loss_on_shard.remote(weights) for w in workers])))
    ctx.stop()
    return loss0, loss1


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-workers", type=int, default=4)
    p.add_argument("--iterations", type=int, default=40)
    a = p.parse_args()
    loss0, loss1 = run(a.num_workers, a.iterations)
    print(f"loss {loss0:.4f} -> {loss1:.4f} "
          f"({a.num_workers} workers, sync PS)")


if __name__ == "__main__":
    main()
