"""Asynchronous parameter server on the actor runtime.

Mirror of the reference example
pyzoo/zoo/examples/ray/parameter_server/async_parameter_server.py: workers
pull weights, compute a gradient and push it back independently — the PS
applies updates as they arrive (Hogwild-style), no global barrier.  Built
on ``analytics_zoo_tpu.parallel.actors`` with the same numpy softmax
model as the sync variant.

Usage: python examples/parameter_server/async_parameter_server.py
       [--num-workers 4] [--updates-per-worker 40]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from analytics_zoo_tpu.parallel.actors import ActorContext, get, remote
from examples.parameter_server.sync_parameter_server import (
    CLASSES,
    DIM,
    softmax_grads,
)


@remote
class ParameterServer:
    def __init__(self, learning_rate=0.3):
        self.lr = learning_rate
        rng = np.random.default_rng(0)
        self.w = (rng.normal(0, 0.01, DIM * CLASSES + CLASSES)
                  .astype(np.float64))
        self.updates = 0

    def push(self, grad):
        """Apply ONE worker's gradient immediately (async semantics)."""
        self.w -= self.lr * grad
        self.updates += 1
        return self.updates

    def pull(self):
        return self.w

    def update_count(self):
        return self.updates


@remote
class AsyncWorker:
    def __init__(self, worker_index, num_workers, batch_size=128):
        from sklearn.datasets import load_digits

        d = load_digits()
        x = (d.images.reshape(-1, DIM) / 16.0).astype(np.float64)
        y = d.target.astype(np.int64)
        self.x = x[worker_index::num_workers]
        self.y = y[worker_index::num_workers]
        self.batch = batch_size
        self.rng = np.random.default_rng(100 + worker_index)

    def grad_at(self, weights):
        idx = self.rng.integers(0, len(self.x), self.batch)
        _, g = softmax_grads(weights, self.x[idx], self.y[idx])
        return g

    def loss_on_shard(self, weights):
        loss, _ = softmax_grads(weights, self.x, self.y)
        return float(loss)


def run(num_workers=4, updates_per_worker=40, lr=0.3):
    ctx = ActorContext.init()
    ps = ParameterServer.remote(lr)
    workers = [AsyncWorker.remote(i, num_workers)
               for i in range(num_workers)]
    w0 = ps.pull.remote().get()
    loss0 = float(np.mean(get(
        [w.loss_on_shard.remote(w0) for w in workers])))

    # async loop: each worker's next gradient is computed at whatever
    # weights it happens to pull — pushes interleave without a barrier
    pending = {w: w.grad_at.remote(w0) for w in workers}
    done = {w: 0 for w in workers}
    while pending:
        for w, ref in list(pending.items()):
            g = ref.get()
            ps.push.remote(g)
            done[w] += 1
            if done[w] < updates_per_worker:
                fresh = ps.pull.remote().get()
                pending[w] = w.grad_at.remote(fresh)
            else:
                del pending[w]

    wN = ps.pull.remote().get()
    loss1 = float(np.mean(get(
        [w.loss_on_shard.remote(wN) for w in workers])))
    total = ps.update_count.remote().get()
    ctx.stop()
    assert total == num_workers * updates_per_worker
    return loss0, loss1


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-workers", type=int, default=4)
    p.add_argument("--updates-per-worker", type=int, default=40)
    a = p.parse_args()
    loss0, loss1 = run(a.num_workers, a.updates_per_worker)
    print(f"loss {loss0:.4f} -> {loss1:.4f} (async PS, "
          f"{a.num_workers} workers)")


if __name__ == "__main__":
    main()
