"""Routed mixture-of-experts transformer training — a capability the
reference never had (SURVEY.md §2.4 makes expert parallelism first-class;
the reference's TransformerLayer.scala:137 feed-forward is a dense MLP).

``TransformerLayer(moe_experts=E, moe_top_k=k)`` swaps every block's
feed-forward for a GShard-style routed MoE (ops/moe.py): top-k routing
with expert capacity behind the residual, the load-balancing auxiliary
loss joining the training loss automatically through the layer-state
channel.  On a mesh with an ``expert`` axis the expert dimension shards
across devices (dryrun phase 6 trains this config on a data x expert
mesh).

The task is the attention example's marker-majority classification, so
the two examples are directly comparable: same data, dense vs MoE FFN.

Usage:
    python examples/moe/train_moe.py --epochs 6 --experts 4
"""

import argparse


def run(epochs=6, n=1024, vocab=128, seq_len=24, batch_size=64,
        experts=4, top_k=2):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Input, Model
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Dense,
        GlobalAveragePooling1D,
        TransformerLayer,
    )
    from examples.attention.transformer import make_data

    init_zoo_context("moe example")
    x, y = make_data(n, vocab, seq_len)
    xv, yv = make_data(256, vocab, seq_len, seed=1)

    tokens = Input(shape=(seq_len,), name="tokens")
    core = TransformerLayer(vocab=vocab, seq_len=seq_len, n_block=2,
                            n_head=4, hidden_size=64,
                            moe_experts=experts, moe_top_k=top_k,
                            name="moe_core")
    seq = core(tokens)
    pooled = GlobalAveragePooling1D()(seq)
    out = Dense(2, activation="softmax")(pooled)
    model = Model(tokens, out, name="moe_transformer")
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=batch_size, nb_epoch=epochs,
              validation_data=(xv, yv))
    res = model.evaluate(xv, yv, batch_size=batch_size)
    # the layer-state channel carries the router health metrics
    moe_state = [v for v in model.state.values()
                 if isinstance(v, dict) and "moe_aux_loss" in v][0]
    res["moe_aux_loss"] = float(moe_state["moe_aux_loss"])
    res["moe_drop_fraction"] = float(moe_state["moe_drop_fraction"])
    return res


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--top-k", type=int, default=2)
    args = ap.parse_args()
    res = run(epochs=args.epochs, experts=args.experts, top_k=args.top_k)
    print(f"validation: {res}")


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
