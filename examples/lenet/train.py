"""LeNet-5 on MNIST — the framework's first-run example.

Reference: the Scala/py LeNet examples (reference
pyzoo/zoo/examples/ + zoo/.../examples/localEstimator/LenetEstimator.scala);
BASELINE.json config 1 ("LeNet on MNIST via Sequential + compile/fit").

Reads the standard MNIST idx files from --data-dir if present; otherwise
generates a procedural stand-in (10 distinguishable glyph classes) so the
example runs end-to-end with zero downloads.

Usage:
    python examples/lenet/train.py --epochs 2 --batch-size 256
    python examples/lenet/train.py --data-dir /data/mnist
"""

import argparse
import gzip
import os
import struct

import numpy as np


def load_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx image magic {magic}"
        return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)


def load_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx label magic {magic}"
        return np.frombuffer(f.read(), np.uint8)


def load_mnist(data_dir):
    def find(stem):
        for suffix in ("", ".gz"):
            p = os.path.join(data_dir, stem + suffix)
            if os.path.exists(p):
                return p
        raise FileNotFoundError(stem)

    xtr = load_idx_images(find("train-images-idx3-ubyte"))
    ytr = load_idx_labels(find("train-labels-idx1-ubyte"))
    xte = load_idx_images(find("t10k-images-idx3-ubyte"))
    yte = load_idx_labels(find("t10k-labels-idx1-ubyte"))
    return (xtr, ytr), (xte, yte)


def synthetic_mnist(n_train=4096, n_test=1024, seed=0):
    """10 glyph classes: a bright square whose (row, col) cell encodes the
    class, plus noise — linearly separable enough that LeNet reaches >90%
    within an epoch, so the example demonstrably *learns*."""
    rng = np.random.default_rng(seed)

    def make(n):
        y = rng.integers(0, 10, n)
        x = rng.normal(16, 8, (n, 28, 28)).clip(0, 255)
        for i, c in enumerate(y):
            r, col = divmod(int(c), 5)
            x[i, 4 + r * 12:14 + r * 12, 2 + col * 5:7 + col * 5] = 250
        return x.astype(np.uint8), y.astype(np.uint8)

    return make(n_train), make(n_test)


def run(data_dir=None, batch_size=256, epochs=2, lr=0.01, limit=4096):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.lenet import build_lenet
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import SGD

    init_zoo_context("lenet example")
    if data_dir:
        (xtr, ytr), (xte, yte) = load_mnist(data_dir)
    else:
        (xtr, ytr), (xte, yte) = synthetic_mnist(limit)

    def prep(x):
        return ((x.astype(np.float32) / 255.0) - 0.1307)[..., None] / 0.3081

    model = build_lenet()
    model.compile(optimizer=SGD(lr=lr, momentum=0.9),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(prep(xtr), ytr.astype(np.int32), batch_size=batch_size,
              nb_epoch=epochs)
    return model.evaluate(prep(xte), yte.astype(np.int32),
                          batch_size=batch_size)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default=None,
                    help="dir with MNIST idx files (default: synthetic)")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--n-train", type=int, default=4096,
                    help="synthetic train size")
    args = ap.parse_args()
    results = run(args.data_dir, args.batch_size, args.epochs, args.lr,
                  args.n_train)
    print({k: round(float(v), 4) for k, v in results.items()})


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
