"""Streaming text classification (reference
pyzoo/zoo/examples/streaming/textclassification/
streaming_text_classification.py: a Spark Structured Streaming loop that
tokenizes arriving lines and classifies them with a trained
TextClassifier).

TPU-native version: the stream is a serving broker (in-memory here; Redis
or the file spool in production — same API), the consumer is the Cluster
Serving micro-batch loop, and the model is a TextClassifier trained
in-process.  New lines are tokenized with the training TextSet's
word index and enqueued; predictions stream back per-uri.

Usage:
    python examples/streaming/streaming_text_classification.py
"""

import argparse
import tempfile
import threading

import numpy as np

_CLASS_WORDS = {0: ["game", "team", "score", "coach", "season"],
                1: ["market", "stock", "trade", "profit", "bank"]}


def make_corpus(n, seq_len, seed=0):
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    filler = ["the", "a", "of", "and", "to", "in", "it", "was"]
    for _ in range(n):
        c = int(rng.integers(0, 2))
        words = [str(rng.choice(_CLASS_WORDS[c])) if rng.random() < 0.4
                 else str(rng.choice(filler)) for _ in range(seq_len)]
        texts.append(" ".join(words))
        labels.append(c)
    return texts, np.asarray(labels, np.int32)


def run(n_stream=6, seq_len=20, epochs=8):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.feature.text import TextSet
    from analytics_zoo_tpu.models.textclassification import TextClassifier
    from analytics_zoo_tpu.serving import (
        ClusterServing,
        ClusterServingHelper,
        InMemoryBroker,
        InputQueue,
        OutputQueue,
    )

    init_zoo_context("streaming text classification")

    # 1. train a TextClassifier on a toy 2-class corpus
    texts, labels = make_corpus(512, seq_len)
    ts = TextSet.from_texts(texts, list(labels)) \
        .tokenize().normalize().word2idx().shape_sequence(seq_len)
    clf = TextClassifier(class_num=2, token_length=32,
                         sequence_length=seq_len, encoder="cnn",
                         vocab_size=len(ts.get_word_index()) + 1)
    clf.compile(optimizer="adam",
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    clf.fit(ts.to_feature_set(), batch_size=64, nb_epoch=epochs)

    tmp = tempfile.mkdtemp()
    model_path = tmp + "/textclassifier.zoo"
    clf.model.save(model_path)

    # 2. stream: broker + serving loop + client
    broker = InMemoryBroker()
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=4,
                             data_shape=(seq_len,),
                             log_dir=tmp + "/logs"),
        broker=broker)
    server = threading.Thread(
        target=lambda: serving.run(max_records=n_stream, idle_timeout=30),
        daemon=True)
    server.start()

    stream_texts, truth = make_corpus(n_stream, seq_len, seed=1)
    inq, outq = InputQueue(broker=broker), OutputQueue(broker=broker)
    word_index = ts.get_word_index()
    for i, line in enumerate(stream_texts):
        toks = [word_index.get(w.lower(), 0) for w in line.split()]
        toks = (toks + [0] * seq_len)[:seq_len]
        inq.enqueue(f"line-{i}", np.asarray(toks, np.float32))
    server.join(timeout=120)

    results = {f"line-{i}": outq.query(f"line-{i}")
               for i in range(n_stream)}
    return results, truth, stream_texts


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=6)
    args = ap.parse_args()
    results, truth, texts = run(n_stream=args.n)
    for i in range(args.n):
        uri = f"line-{i}"
        print(f"{uri}: pred={results[uri]} true={truth[i]} "
              f"| {texts[i][:48]}...")


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
