"""Streaming object detection (reference
pyzoo/zoo/examples/streaming/objectdetection/: a path-stream of image
files is consumed, each image runs through an ObjectDetector, and
box-annotated copies are written to an output folder; a companion
image_path_writer feeds the stream).

TPU-native version: the stream is a watched spool directory (same
file-queue idea, no Spark Streaming), the detector is the SSD zoo model
trained on the checked-in VOCmini fixture, and ``visualize`` draws the
boxes.  Self-contained: trains, stages a few images into the spool,
consumes them, writes annotated .npy images to --out-dir.

Usage:
    python examples/streaming/streaming_object_detection.py --epochs 20
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def stage_images(spool_dir, images, interval=0.0):
    """The image_path_writer role: drop image arrays into the spool."""
    import numpy as np

    for i, img in enumerate(images):
        tmp = os.path.join(spool_dir, f".tmp-{i}.npy")
        np.save(tmp, img)
        os.replace(tmp, os.path.join(spool_dir, f"img-{i}.npy"))
        if interval:
            time.sleep(interval)


def consume_stream(detector, spool_dir, out_dir, expected,
                   conf_threshold=0.05, timeout=60.0, poll=0.2):
    """Watch the spool, detect, write annotated images; returns the
    per-image detections."""
    import numpy as np

    os.makedirs(out_dir, exist_ok=True)
    seen, results = set(), {}
    deadline = time.monotonic() + timeout
    while len(results) < expected and time.monotonic() < deadline:
        pending = sorted(f for f in os.listdir(spool_dir)
                         if f.endswith(".npy") and f not in seen)
        if not pending:
            time.sleep(poll)
            continue
        batch = [np.load(os.path.join(spool_dir, f)) for f in pending]
        dets = detector.predict_image_set(
            np.stack(batch), conf_threshold=conf_threshold)
        for fname, img, det in zip(pending, batch, dets):
            seen.add(fname)
            # draw everything the detector reported (visualize's own
            # default threshold is stricter than conf_threshold)
            annotated = detector.visualize(
                img, det, score_threshold=conf_threshold)
            np.save(os.path.join(out_dir, fname), annotated)
            results[fname] = det
    return results


def run(epochs=20, n_stream=4, out_dir=None, resolution=64, max_boxes=4):
    import numpy as np

    from examples.objectdetection.train_ssd import (
        MINI_CLASSES,
        VOC_MINI,
        run as train_ssd,
    )

    # 1. a trained detector (VOCmini fixture; the reference loads a
    #    published zoo .model file instead)
    _, det = train_ssd(epochs=epochs, resolution=resolution,
                       max_boxes=max_boxes)

    # 2. stage the stream: the val images of the same fixture, prepared
    #    with the same geometry the detector was trained on
    from analytics_zoo_tpu.feature.image import ssd_val_set
    from analytics_zoo_tpu.models.image.objectdetection import PascalVoc

    class_map = {c: float(i + 1) for i, c in enumerate(MINI_CLASSES)}
    recs = PascalVoc(VOC_MINI, "2007", "val",
                     class_to_ind=class_map).roidb()
    val = ssd_val_set(recs, resolution=resolution, max_boxes=max_boxes,
                      label_offset=-1)
    imgs = next(iter(val.batches(max(n_stream, 1), shuffle=False,
                                 drop_last=False)))["x"][:n_stream]

    spool = tempfile.mkdtemp(prefix="od-stream-")
    out_dir = out_dir or tempfile.mkdtemp(prefix="od-out-")
    try:
        stage_images(spool, list(imgs))
        results = consume_stream(det, spool, out_dir, expected=len(imgs))
    finally:
        shutil.rmtree(spool, ignore_errors=True)
    return results, out_dir


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--out-dir", default=None)
    args = ap.parse_args()
    results, out_dir = run(epochs=args.epochs, n_stream=args.n,
                           out_dir=args.out_dir)
    for fname, det in sorted(results.items()):
        n = len(det.get("boxes", []))
        print(f"{fname}: {n} detection(s) -> {out_dir}/{fname}")


if __name__ == "__main__":
    main()
