"""QA ranking example — KNRM over question/answer relations (reference
pyzoo/zoo/examples/qaranker/qa_ranker.py: TextSet relations ->
KNRM + RankHinge -> ndcg/MAP evaluation).

With --data-dir, expects ``questions.csv``/``answers.csv`` (uri,text) and
``relations.csv`` (q_uri,a_uri,label).  Without, a synthetic corpus where
the right answer shares rare tokens with its question.

Usage:
    python examples/qaranker/train.py --epochs 6
"""

import argparse
import os

import numpy as np


def load_relations(data_dir=None, n_q=60, n_per_q=4, seed=0):
    from analytics_zoo_tpu.feature.text import Relation, TextSet

    if data_dir:
        q = TextSet.read_csv(os.path.join(data_dir, "questions.csv"))
        a = TextSet.read_csv(os.path.join(data_dir, "answers.csv"))
        rels = []
        with open(os.path.join(data_dir, "relations.csv")) as f:
            for line in f:
                i1, i2, lab = line.strip().split(",")
                rels.append(Relation(i1, i2, int(lab)))
        return q, a, rels
    rng = np.random.default_rng(seed)
    qs, ans, rels = [], [], []
    for qi in range(n_q):
        key = f"key{qi}"
        qs.append((f"q{qi}", f"what is {key} about common topic"))
        for ai in range(n_per_q):
            uri = f"a{qi}_{ai}"
            if ai == 0:
                ans.append((uri, f"the answer involving {key} exactly"))
                rels.append(Relation(f"q{qi}", uri, 1))
            else:
                other = f"key{int(rng.integers(n_q))}"
                ans.append((uri, f"some unrelated text about {other}"))
                rels.append(Relation(f"q{qi}", uri, 0))
    from analytics_zoo_tpu.feature.text import TextSet as TS
    q_set = TS([_feat(u, t) for u, t in qs])
    a_set = TS([_feat(u, t) for u, t in ans])
    return q_set, a_set, rels


def _feat(uri, text):
    from analytics_zoo_tpu.feature.text.textset import TextFeature

    return TextFeature(text, uri=uri)


def run(data_dir=None, q_len=10, a_len=12, epochs=6, batch_size=32):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.feature.text import TextSet
    from analytics_zoo_tpu.models.textmatching import KNRM

    init_zoo_context("qa ranker")
    q_set, a_set, rels = load_relations(data_dir)
    q_set.tokenize().normalize().word2idx().shape_sequence(q_len)
    a_set.tokenize().normalize().word2idx(
        existing_map=q_set.get_word_index()).shape_sequence(a_len)
    vocab = len(q_set.get_word_index()) + 1

    n_train = int(0.8 * len(rels))
    q_pairs, d_pairs, y = TextSet.from_relation_pairs(
        rels[:n_train], q_set, a_set)

    knrm = KNRM(q_len, a_len, vocab_size=vocab, embed_size=32,
                target_mode="ranking")
    knrm.compile(optimizer="adam", loss="rank_hinge")
    knrm.fit([q_pairs, d_pairs], y, batch_size=batch_size, nb_epoch=epochs)

    # listwise eval on held-out relations (Ranker.ndcg / recall_top_k)
    t1 = {f.uri: f.indices for f in q_set.features}
    t2 = {f.uri: f.indices for f in a_set.features}
    by_q: dict = {}
    for r in rels[n_train:]:
        by_q.setdefault(r.id1, []).append(r)
    y_groups, s_groups = [], []
    for q, rs in by_q.items():
        qx = np.stack([t1[r.id1] for r in rs])
        ax = np.stack([t2[r.id2] for r in rs])
        scores = np.asarray(knrm.predict([qx, ax])).reshape(-1)
        y_groups.append(np.asarray([r.label for r in rs], np.float32))
        s_groups.append(scores)
    return {"ndcg@3": KNRM.ndcg(y_groups, s_groups, 3),
            "recall@1": KNRM.recall_top_k(y_groups, s_groups, 1)}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--epochs", type=int, default=6)
    args = ap.parse_args()
    print({k: round(v, 4) for k, v in run(args.data_dir,
                                          epochs=args.epochs).items()})


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
