"""Transformer sequence classification (reference
pyzoo/zoo/examples/attention/transformer.py: a TransformerLayer stack over
IMDB token/position inputs, pooled into a 2-class softmax).

Self-contained: synthetic token sequences whose class is decided by which
marker-token family occurs more often — attention has to aggregate over the
whole sequence, chance is 0.5.  The whole model (embedding, n_block
self-attention blocks, pooling, head) lowers to one jitted XLA program.

Usage:
    python examples/attention/transformer.py --epochs 8
"""

import argparse

import numpy as np


def make_data(n, vocab, seq_len, seed=0):
    """Class 1 iff more tokens from [2, 12) than from [12, 22)."""
    rng = np.random.default_rng(seed)
    x = rng.integers(22, vocab, size=(n, seq_len))
    n_mark = rng.integers(2, seq_len // 2, size=n)
    for i in range(n):
        pos = rng.choice(seq_len, size=n_mark[i], replace=False)
        fam = rng.integers(0, 2)
        lo = 2 if fam else 12
        x[i, pos] = rng.integers(lo, lo + 10, size=n_mark[i])
        # tie-break: guarantee a strict majority for the chosen family
    counts_pos = ((x >= 2) & (x < 12)).sum(1)
    counts_neg = ((x >= 12) & (x < 22)).sum(1)
    y = (counts_pos > counts_neg).astype(np.int32)
    return x.astype(np.int32), y


def run(epochs=8, n=1024, vocab=128, seq_len=24, batch_size=64):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Input, Model
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Dense,
        Dropout,
        GlobalAveragePooling1D,
        TransformerLayer,
    )

    init_zoo_context("transformer example")
    x, y = make_data(n, vocab, seq_len)
    xv, yv = make_data(256, vocab, seq_len, seed=1)

    tokens = Input(shape=(seq_len,), name="tokens")
    seq = TransformerLayer(vocab=vocab, seq_len=seq_len, n_block=2,
                           n_head=4, hidden_size=64)(tokens)
    pooled = GlobalAveragePooling1D()(seq)
    pooled = Dropout(0.1)(pooled)
    out = Dense(2, activation="softmax")(pooled)
    model = Model(tokens, out, name="transformer_classifier")
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=batch_size, nb_epoch=epochs,
              validation_data=(xv, yv))
    return model.evaluate(xv, yv, batch_size=batch_size)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()
    res = run(epochs=args.epochs, batch_size=args.batch_size)
    print(f"validation: {res}")


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
