"""Cluster Serving demo — embedded server + client (reference
serving/ClusterServing.scala loop + pyzoo/zoo/serving/client.py usage:
enqueue images to the stream, server micro-batches + predicts + writes
results back, client queries them).

Runs fully self-contained: trains a tiny classifier, starts the serving
loop on a background thread over an in-memory broker (use --spool DIR for
the multi-process FileBroker instead), pushes images, prints predictions.

Usage:
    python examples/serving/demo.py --n 8
"""

import argparse
import tempfile
import threading

import numpy as np


def make_model(path, size=8):
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Flatten

    m = Sequential()
    m.add(Flatten(input_shape=(size, size, 1)))
    m.add(Dense(2, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    rng = np.random.default_rng(0)
    x = rng.random((128, size, size, 1)).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > 0.5).astype(np.int32)
    m.fit(x, y, batch_size=32, nb_epoch=10)
    m.save(path, over_write=True)
    return path


def run(n=8, size=8, spool=None):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.serving import (
        ClusterServing,
        ClusterServingHelper,
        FileBroker,
        InMemoryBroker,
        InputQueue,
        OutputQueue,
    )

    init_zoo_context("serving demo")
    tmp = tempfile.mkdtemp()
    model_path = make_model(tmp + "/model.zoo", size)
    broker = FileBroker(spool) if spool else InMemoryBroker()
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=4,
                             data_shape=(size, size, 1),
                             log_dir=tmp + "/logs"),
        broker=broker)
    server = threading.Thread(
        target=lambda: serving.run(max_records=n), daemon=True)
    server.start()

    inq = InputQueue(broker=broker)
    outq = OutputQueue(broker=broker)
    rng = np.random.default_rng(1)
    expected = []
    for i in range(n):
        img = rng.random((size, size, 1)).astype(np.float32)
        expected.append(int(img.mean() > 0.5))
        inq.enqueue_image(f"img-{i}", img)
    server.join(timeout=60)

    results = {}
    for i in range(n):
        results[f"img-{i}"] = outq.query(f"img-{i}")
    return results, expected


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=8)
    ap.add_argument("--spool", default=None,
                    help="directory for a FileBroker (default: in-memory)")
    args = ap.parse_args()
    results, expected = run(args.n, spool=args.spool)
    for (uri, res), exp in zip(sorted(results.items()), expected):
        print(f"{uri}: {res}  (true class {exp})")


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
