"""Distributed policy-gradient RL on the actor runtime (reference
pyzoo/zoo/examples/ray/rl_pong/rl_pong.py: Karpathy's numpy Pong policy
gradient with N `@ray.remote` rollout actors on RayOnSpark — each worker
plays episodes at the current weights and ships back gradients, the
driver applies RMSProp as results arrive).

Same structure, no Atari/gym dependency (zero egress in this sandbox):
the environment is "catch" — a ball falls down a WxH pixel board, a
paddle moves left/right/stay, +1 for a catch, -1 for a miss — and the
policy is the reference's numpy recipe: 2-layer MLP over pixels,
discounted-reward REINFORCE with manual backprop, RMSProp on the driver.
The DISTRIBUTION pattern (broadcast weights -> parallel rollout actors
-> gradient aggregation per round) is the example's point.

Usage: python examples/ray_rl/rl_pong.py [--rounds 30] [--workers 3]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from analytics_zoo_tpu.parallel.actors import (  # noqa: E402
    ActorContext,
    get,
    remote,
)

W, HGT = 7, 8             # board width/height (the "pixels")
D = W * HGT               # input dimensionality
H = 32                    # hidden neurons (reference uses 200 for Atari)
GAMMA = 0.97
DECAY = 0.99              # RMSProp decay (reference decay_rate)
LR = 1e-2
ACTIONS = 3               # left / stay / right


def init_weights(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w1": (rng.standard_normal((D, H)) / np.sqrt(D)).astype(np.float64),
        "w2": (rng.standard_normal((H, ACTIONS))
               / np.sqrt(H)).astype(np.float64),
    }


def discount_rewards(r):
    """Reference discount_rewards: gamma-discounted return per step."""
    out = np.zeros_like(r)
    acc = 0.0
    for t in reversed(range(len(r))):
        acc = acc * GAMMA + r[t]
        out[t] = acc
    return out


@remote
class RolloutWorker:
    """Plays episodes at given weights; returns policy gradients
    (reference PongEnv.compute_gradient)."""

    def __init__(self, seed):
        self.rng = np.random.default_rng(seed)

    def _episode(self, w):
        ball_x = int(self.rng.integers(W))
        paddle_x = W // 2
        xs, hs, dlogps, rewards = [], [], [], []
        for ball_y in range(HGT - 1):
            board = np.zeros((HGT, W))
            board[ball_y, ball_x] = 1.0
            board[HGT - 1, paddle_x] = 1.0
            x = board.reshape(-1)
            h = np.maximum(x @ w["w1"], 0.0)
            logits = h @ w["w2"]
            p = np.exp(logits - logits.max())
            p /= p.sum()
            a = int(self.rng.choice(ACTIONS, p=p))
            # grad of log pi(a|x) wrt logits
            dlogp = -p
            dlogp[a] += 1.0
            paddle_x = int(np.clip(paddle_x + (a - 1), 0, W - 1))
            done = ball_y == HGT - 2
            rewards.append((1.0 if paddle_x == ball_x else -1.0)
                           if done else 0.0)
            xs.append(x)
            hs.append(h)
            dlogps.append(dlogp)
        return (np.stack(xs), np.stack(hs), np.stack(dlogps),
                np.asarray(rewards))

    def compute_gradient(self, weights, episodes=8):
        """N episodes at ``weights`` -> (grads, mean reward).

        Advantages are normalized across the WHOLE episode batch (the
        reference's recipe) — per-episode normalization would erase the
        won-vs-lost signal that IS the gradient."""
        all_xs, all_hs, all_dlogps, all_adv = [], [], [], []
        total = 0.0
        for _ in range(episodes):
            xs, hs, dlogps, rewards = self._episode(weights)
            total += rewards.sum()
            all_xs.append(xs)
            all_hs.append(hs)
            all_dlogps.append(dlogps)
            all_adv.append(discount_rewards(rewards))
        xs = np.concatenate(all_xs)
        hs = np.concatenate(all_hs)
        dlogps = np.concatenate(all_dlogps)
        adv = np.concatenate(all_adv)
        adv -= adv.mean()
        std = adv.std()
        if std > 1e-8:
            adv /= std
        dlogits = dlogps * adv[:, None]     # (T_total, A)
        g = {
            "w2": hs.T @ dlogits,
            "w1": xs.T @ ((dlogits @ weights["w2"].T) * (hs > 0)),
        }
        return g, total / episodes


def run(rounds=30, workers=3, episodes_per_worker=8, seed=0):
    ctx = ActorContext.init()
    w = init_weights(seed)
    rms = {k: np.zeros_like(v) for k, v in w.items()}
    actors = [RolloutWorker.remote(seed + 100 + i) for i in range(workers)]

    history = []
    for rnd in range(rounds):
        results = get([a.compute_gradient.remote(w, episodes_per_worker)
                       for a in actors])
        mean_reward = float(np.mean([r for _, r in results]))
        history.append(mean_reward)
        for k in w:
            grad = np.mean([g[k] for g, _ in results], axis=0)
            rms[k] = DECAY * rms[k] + (1 - DECAY) * grad ** 2
            w[k] += LR * grad / (np.sqrt(rms[k]) + 1e-5)
        if (rnd + 1) % 10 == 0:
            print(f"round {rnd + 1}: mean episode reward "
                  f"{mean_reward:+.3f}")
    ctx.stop()
    first = float(np.mean(history[:5]))
    last = float(np.mean(history[-5:]))
    print(f"mean reward first 5 rounds {first:+.3f} -> last 5 {last:+.3f}")
    return first, last


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=30)
    ap.add_argument("--workers", type=int, default=3)
    a = ap.parse_args()
    first, last = run(rounds=a.rounds, workers=a.workers)
    assert last > first, (first, last)


if __name__ == "__main__":
    main()
