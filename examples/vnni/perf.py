"""Inference throughput harness — the reference's int8 Perf role
(zoo/.../examples/vnni/bigdl/Perf.scala:53-66: load a (quantized) model,
run batches, print images/sec; VNNI int8 on Xeon there, int8 weight
quantization + XLA here).

Times f32 vs int8-quantized weights on a ResNet forward pass and reports
quantization error and size reduction — the capability pair behind the
reference's "int8: 4x model size down, up to 2x speedup" claim.

Usage:
    python examples/vnni/perf.py --batch 32 --iters 10
"""

import argparse
import time

import numpy as np


def run(batch=32, iters=10, image_size=64, depth=18):
    import jax

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.resnet import ResNet
    from analytics_zoo_tpu.pipeline.inference.quantize import (
        QuantizedTensor,
        dequantize_params,
        quantization_error,
        quantize_params,
    )

    init_zoo_context("vnni perf")
    net = ResNet.image_net(depth, classes=10,
                           input_shape=(image_size, image_size, 3))
    net.build_params()
    x = np.random.default_rng(0).normal(
        size=(batch, image_size, image_size, 3)).astype(np.float32)

    fwd = jax.jit(lambda p, xx: net.forward(p, xx, state=net.state)[0])

    def timed(params):
        out = fwd(params, x)
        float(np.asarray(out).sum())  # fetch-forced warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fwd(params, x)
        float(np.asarray(out).sum())
        return batch * iters / (time.perf_counter() - t0)

    ips_f32 = timed(net.params)

    qparams = quantize_params(net.params, min_size=1024)
    deq = dequantize_params(qparams)
    err = quantization_error(net.params, qparams)

    def nbytes(tree):
        total = 0
        for leaf in jax.tree_util.tree_leaves(
                tree, is_leaf=lambda l: isinstance(l, QuantizedTensor)):
            if isinstance(leaf, QuantizedTensor):
                total += leaf.values.nbytes + leaf.scale.nbytes
            else:
                total += np.asarray(leaf).nbytes
        return total

    ips_deq = timed(deq)
    return {
        "images_per_sec_f32": round(ips_f32, 1),
        "images_per_sec_int8_weights": round(ips_deq, 1),
        "model_bytes_f32": nbytes(net.params),
        "model_bytes_int8": nbytes(qparams),
        "size_reduction": round(nbytes(net.params) / nbytes(qparams), 2),
        "max_quant_error": round(float(err), 5),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--image-size", type=int, default=64)
    args = ap.parse_args()
    import json

    print(json.dumps(run(args.batch, args.iters, args.image_size)))


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
