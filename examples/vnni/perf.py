"""Inference throughput harness — the reference's int8 Perf role
(zoo/.../examples/vnni/bigdl/Perf.scala:53-66: load a (quantized) model,
run batches, print images/sec; VNNI int8 on Xeon there, int8 weight
quantization + XLA here).

Times f32 vs int8-quantized weights vs calibrated int8 (activations too)
on a device-resident ResNet forward pass and reports quantization error
and size reduction — the capability pair behind the reference's "int8: 4x
model size down, up to 2x speedup" claim.  Honest TPU result (v5e,
ResNet-18 @128²): the 4x size/accuracy side holds (max weight error
~0.9%, argmax agreement ~1.0) but int8 execution is ~1.7x SLOWER than
f32 — XLA lowers these convs without a native int8 fast path, and bf16/
f32 convs are already MXU-native; the 2x speedup is a Xeon-VNNI
property, not a TPU one.  Use int8 here for model size/HBM footprint.

Usage:
    python examples/vnni/perf.py --batch 32 --iters 10
"""

import argparse
import time

import numpy as np


def run(batch=32, iters=10, image_size=64, depth=18):
    import jax

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.resnet import ResNet
    from analytics_zoo_tpu.pipeline.inference.quantize import (
        QuantizedTensor,
        dequantize_params,
        quantization_error,
        quantize_params,
    )

    init_zoo_context("vnni perf")
    net = ResNet.image_net(depth, classes=10,
                           input_shape=(image_size, image_size, 3))
    net.build_params()
    x = np.random.default_rng(0).normal(
        size=(batch, image_size, image_size, 3)).astype(np.float32)

    fwd = jax.jit(lambda p, xx: net.forward(p, xx, state=net.state)[0])

    # device-resident input: this harness's host->device link is ~30 MB/s
    # (PROFILE_r03/ANALYSIS.md), so re-uploading the batch per call would
    # measure the tunnel, not the compute path being compared
    xd = jax.device_put(x)

    def timed(params, fn=None):
        fn = fn or fwd
        out = fn(params, xd)
        float(np.asarray(out).sum())  # fetch-forced warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(params, xd)
        float(np.asarray(out).sum())
        return batch * iters / (time.perf_counter() - t0)

    ips_f32 = timed(net.params)

    qparams = quantize_params(net.params, min_size=1024)
    deq = dequantize_params(qparams)
    err = quantization_error(net.params, qparams)

    def nbytes(tree):
        total = 0
        for leaf in jax.tree_util.tree_leaves(
                tree, is_leaf=lambda l: isinstance(l, QuantizedTensor)):
            if isinstance(leaf, QuantizedTensor):
                total += leaf.values.nbytes + leaf.scale.nbytes
            else:
                total += np.asarray(leaf).nbytes
        return total

    ips_deq = timed(deq)

    # calibrated int8: activations quantized too, conv/dense run
    # int8 x int8 -> int32 (the InferenceModel.optimize("int8",
    # calibration_data=...) path); timed on the same device-resident batch
    from analytics_zoo_tpu.pipeline.inference.quantize import (
        quantize_model,
    )

    q = quantize_model(net, x[: min(batch, 64)])
    with q.installed():
        fwd_cal = jax.jit(lambda p, xx: net.forward(
            p, xx, state=net.state, training=False)[0])
        ips_cal = timed(q.qparams, fwd_cal)

    return {
        "images_per_sec_f32": round(ips_f32, 1),
        "images_per_sec_int8_weights": round(ips_deq, 1),
        "images_per_sec_int8_calibrated": round(ips_cal, 1),
        "model_bytes_f32": nbytes(net.params),
        "model_bytes_int8": nbytes(qparams),
        "size_reduction": round(nbytes(net.params) / nbytes(qparams), 2),
        "max_quant_error": round(float(err), 5),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--image-size", type=int, default=64)
    args = ap.parse_args()
    import json

    print(json.dumps(run(args.batch, args.iters, args.image_size)))


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
