"""Object-detection inference + visualization (reference
pyzoo/zoo/examples/objectdetection/predict.py: load an ObjectDetector,
predict an image set, draw boxes with the Visualizer).

Trains the tiny SSD on the checked-in VOCmini fixture first (no
pretrained-model downloads in this sandbox), then runs the reference's
predict->visualize flow and writes annotated images.

Usage: python examples/objectdetection/predict.py [--out-dir /tmp/dets]
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from examples.objectdetection.train_ssd import MINI_CLASSES, VOC_MINI, run


def predict_and_visualize(out_dir=None, epochs=30, conf=0.3):
    out_dir = out_dir or tempfile.mkdtemp()
    os.makedirs(out_dir, exist_ok=True)
    # train the tiny detector on the fixture (stands in for load_model)
    _, det = run(epochs=epochs)

    from analytics_zoo_tpu.feature.image import ssd_val_set
    from analytics_zoo_tpu.models.image.objectdetection import PascalVoc

    class_map = {c: float(i + 1) for i, c in enumerate(MINI_CLASSES)}
    recs = PascalVoc(VOC_MINI, "2007", "val",
                     class_to_ind=class_map).roidb()
    val = ssd_val_set(recs, resolution=64, max_boxes=4, label_offset=-1)
    batches = list(val.batches(4, shuffle=False, drop_last=False))
    images = np.concatenate([b["x"] for b in batches])

    detections = det.predict_image_set(images, conf_threshold=conf)
    written = []
    for i, (img, dets_i) in enumerate(zip(images, detections)):
        img8 = np.clip(np.asarray(img) * 255.0, 0, 255).astype(np.uint8) \
            if np.asarray(img).dtype != np.uint8 else np.asarray(img)
        annotated = det.visualize(img8, dets_i)
        path = os.path.join(out_dir, f"det_{i:03d}.png")
        try:
            import cv2

            cv2.imwrite(path, np.asarray(annotated)[..., ::-1])
            written.append(path)
        except ImportError:
            np.save(path.replace(".png", ".npy"), np.asarray(annotated))
            written.append(path.replace(".png", ".npy"))
    n_boxes = sum(len(d["boxes"]) for d in detections)
    print(f"wrote {len(written)} annotated images ({n_boxes} boxes) "
          f"to {out_dir}")
    return written, detections


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out-dir", default=None)
    p.add_argument("--epochs", type=int, default=30)
    a = p.parse_args()
    predict_and_visualize(out_dir=a.out_dir, epochs=a.epochs)


if __name__ == "__main__":
    main()
