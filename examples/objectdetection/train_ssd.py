"""SSD object-detection training example — Pascal VOC (reference
zoo/.../examples/objectdetection + SSDDataSet.scala pipeline:
VOC -> roi transforms -> SSD -> MultiBoxLoss -> mAP).

--voc-root points at a VOCdevkit folder; the default is the checked-in
VOCmini fixture (3 classes), so the example always runs.

Usage:
    python examples/objectdetection/train_ssd.py --epochs 30
"""

import argparse
import os

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
VOC_MINI = os.path.join(REPO, "tests", "resources", "VOCmini")
MINI_CLASSES = ("car", "person", "dog")


def run(voc_root=VOC_MINI, year="2007", classes=MINI_CLASSES,
        resolution=64, variant="ssd-tiny", epochs=30, batch_size=8,
        max_boxes=4, lr=1e-3):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.feature.image import ssd_train_set, ssd_val_set
    from analytics_zoo_tpu.models.image.objectdetection import (
        ObjectDetector,
        PascalVoc,
        mean_average_precision,
    )
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    init_zoo_context("ssd voc")
    class_map = {c: float(i + 1) for i, c in enumerate(classes)}
    train_recs = PascalVoc(voc_root, year, "train",
                           class_to_ind=class_map).roidb()
    val_recs = PascalVoc(voc_root, year, "val",
                         class_to_ind=class_map).roidb()
    train = ssd_train_set(train_recs, resolution=resolution,
                          max_boxes=max_boxes, label_offset=-1)
    val = ssd_val_set(val_recs, resolution=resolution,
                      max_boxes=max_boxes, label_offset=-1)

    val_batches = list(val.batches(batch_size, shuffle=False,
                                   drop_last=False))
    val_x = np.concatenate([b["x"] for b in val_batches])
    gts = [dict(boxes=r[r[:, 4] >= 0][:, :4], classes=r[r[:, 4] >= 0][:, 4])
           for b in val_batches for r in b["y"]]

    det = ObjectDetector(variant, class_names=classes)
    det.compile(Adam(lr=lr))
    det.model.fit(train, batch_size=batch_size, nb_epoch=epochs)
    dets = det.predict_image_set(val_x, conf_threshold=0.05)
    m = mean_average_precision(dets, gts, len(classes), iou_threshold=0.3)
    return m, det


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--voc-root", default=VOC_MINI,
                    help="VOCdevkit folder (default: VOCmini fixture)")
    ap.add_argument("--year", default="2007")
    ap.add_argument("--variant", default="ssd-tiny",
                    choices=("ssd-tiny", "ssd-vgg16-300"))
    ap.add_argument("--resolution", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=8)
    args = ap.parse_args()
    m, _ = run(args.voc_root, args.year, resolution=args.resolution,
               variant=args.variant, epochs=args.epochs,
               batch_size=args.batch_size)
    print(f"VOC mAP@0.3: {m:.3f}")


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
