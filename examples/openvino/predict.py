"""Optimized-inference predict (reference
pyzoo/zoo/examples/openvino/predict.py: a TF object-detection model
converted to OpenVINO IR, loaded with InferenceModel.load_openvino, and
predicted over images; OpenVINO is Xeon's inference accelerator).

The TPU-native counterpart of "load an optimized model and predict" is
:class:`InferenceModel` with ``optimize()``: shape-bucketed AOT jit
compilation, a persistent compile cache, and int8 weight(+activation)
quantization — XLA plays OpenVINO's role.  This example loads a trained
classifier, optimizes it, and predicts a directory of images.

Usage: python examples/openvino/predict.py [--n 32]
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def run(n=32, size=32, precision="int8"):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D, Dense, Flatten, MaxPooling2D,
    )
    from analytics_zoo_tpu.pipeline.inference import InferenceModel

    init_zoo_context("openvino-equivalent predict", seed=0)

    # train a small classifier (stands in for the model-zoo download)
    rng = np.random.default_rng(0)
    y = rng.integers(0, 2, size=256).astype(np.int32)
    x = np.stack([
        np.clip((0.25 if c == 0 else 0.75)
                + rng.normal(0, 0.08, (size, size, 3)), 0, 1)
        for c in y
    ]).astype(np.float32)
    net = Sequential()
    net.add(Convolution2D(8, 3, 3, activation="relu",
                          input_shape=(size, size, 3)))
    net.add(MaxPooling2D((2, 2)))
    net.add(Flatten())
    net.add(Dense(2, activation="softmax"))
    net.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    net.fit(x, y, batch_size=64, nb_epoch=8)
    path = os.path.join(tempfile.mkdtemp(), "model.zoo")
    net.save(path)

    # the reference flow: InferenceModel.load -> optimize -> predict
    model = InferenceModel(concurrent_num=2)
    model.load(path)
    if precision:
        model.optimize(precision=precision, calibration_data=x[:64])

    imgs = np.stack([
        np.clip((0.25 if c == 0 else 0.75)
                + rng.normal(0, 0.08, (size, size, 3)), 0, 1)
        for c in rng.integers(0, 2, size=n)
    ]).astype(np.float32)
    probs = np.asarray(model.predict(imgs))
    classes = probs.argmax(1)
    ref = np.asarray(net.predict(imgs, batch_size=n)).argmax(1)
    agree = float((classes == ref).mean())
    print(f"predicted {n} images ({precision or 'f32'}); "
          f"agreement with the f32 source model: {agree:.2f}")
    return agree


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=32)
    ap.add_argument("--precision", default="int8",
                    choices=["int8", "bf16", ""])
    a = ap.parse_args()
    agree = run(n=a.n, precision=a.precision)
    assert agree > 0.9, agree


if __name__ == "__main__":
    main()
