"""Inference through a TensorFlow SavedModel (reference
pyzoo/zoo/examples/tensorflow/tfnet/predict.py: load a frozen/exported TF
model as TFNet and run distributed predict over images).

TPU-native version: the TF graph executes host-side via ``pure_callback``
inside the jitted predict graph (TFNet); batching/padding/mesh sharding
are the framework's.  Offline-safe: a small tf.keras CNN is exported to a
SavedModel on the fly — point --saved-model at any export dir to use a
real one.

Usage: python examples/tfnet/predict.py [--n 32]
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def export_model(size=32, classes=4):
    import tensorflow as tf

    tf.keras.utils.set_random_seed(0)
    km = tf.keras.Sequential([
        tf.keras.layers.Conv2D(8, 3, strides=2, activation="relu"),
        tf.keras.layers.GlobalAveragePooling2D(),
        tf.keras.layers.Dense(classes, activation="softmax"),
    ])
    km.build((None, size, size, 3))
    d = tempfile.mkdtemp()

    @tf.function(input_signature=[
        tf.TensorSpec([None, size, size, 3], tf.float32)])
    def serve(x):
        return km(x)

    tf.saved_model.save(km, d, signatures=serve)
    return d, km


def run(n=32, size=32, saved_model=None):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.net import Net

    init_zoo_context("tfnet predict", seed=0)
    km = None
    if saved_model is None:
        saved_model, km = export_model(size)
    net = Net.load_tf(saved_model, input_shape=(size, size, 3))
    m = Sequential()
    m.add(net)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, size, size, 3)).astype(np.float32)
    probs = np.asarray(m.predict(x))
    print(f"predicted {probs.shape} via TFNet")
    if km is not None:
        ref = km(x).numpy()
        err = float(np.max(np.abs(probs - ref)))
        agree = float((probs.argmax(1) == ref.argmax(1)).mean())
        print(f"max |zoo - tf| = {err:.2e}; argmax agreement {agree:.2f}")
        return err, agree
    return None, None


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=32)
    p.add_argument("--saved-model", default=None)
    a = p.parse_args()
    run(n=a.n, saved_model=a.saved_model)


if __name__ == "__main__":
    main()
