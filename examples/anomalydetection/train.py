"""Anomaly detection example — NYC-taxi style time series (reference
pyzoo/zoo/examples/anomalydetection/anomaly_detection.py: unroll a
univariate series, train the LSTM-stack AnomalyDetector, flag the points
with the largest prediction error).

With --csv, expects ``timestamp,value`` lines; without, a synthetic
seasonal series with injected anomalies.

Usage:
    python examples/anomalydetection/train.py --epochs 5
"""

import argparse

import numpy as np


def load_series(csv=None, n=2000, seed=0):
    if csv:
        vals = []
        with open(csv) as f:
            for line in f:
                parts = line.strip().split(",")
                try:
                    vals.append(float(parts[-1]))
                except ValueError:
                    continue  # header
        return np.asarray(vals, np.float32), None
    rng = np.random.default_rng(seed)
    t = np.arange(n)
    series = (np.sin(2 * np.pi * t / 48) + 0.5 * np.sin(2 * np.pi * t / 7)
              + 0.05 * rng.standard_normal(n)).astype(np.float32)
    anomalies = rng.choice(n - 200, size=8, replace=False) + 100
    series[anomalies] += rng.choice([-1, 1], size=8) * 1.5
    return series, set(int(a) for a in anomalies)


def run(csv=None, unroll_length=24, epochs=5, batch_size=64,
        anomaly_size=8):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.anomalydetection import AnomalyDetector

    init_zoo_context("anomaly detection")
    series, injected = load_series(csv)
    mean, std = series.mean(), series.std() + 1e-8
    normed = ((series - mean) / std)[:, None]
    x, y = AnomalyDetector.unroll(normed, unroll_length)
    n_train = int(0.8 * len(x))

    model = AnomalyDetector(feature_shape=(unroll_length, 1))
    model.compile(optimizer="adam", loss="mse")
    model.fit(x[:n_train], y[:n_train], batch_size=batch_size,
              nb_epoch=epochs)
    y_pred = model.predict(x[n_train:], batch_size=batch_size)
    anomalies = AnomalyDetector.detect_anomalies(
        y[n_train:], np.asarray(y_pred).reshape(-1), anomaly_size)
    return anomalies, n_train + unroll_length, injected


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--csv", default=None,
                    help="timestamp,value series (default: synthetic)")
    ap.add_argument("--unroll", type=int, default=24)
    ap.add_argument("--epochs", type=int, default=5)
    args = ap.parse_args()
    anomalies, offset, injected = run(args.csv, args.unroll, args.epochs)
    # detect_anomalies returns (y_true, y_pred, is_anomaly) per point
    idx = [i + offset for i, (_, _, flag) in enumerate(anomalies) if flag]
    print(f"flagged {len(idx)} anomalies at series positions {idx}")
    if injected is not None:
        hits = sum(any(abs(i - a) <= 2 for a in injected) for i in idx)
        print(f"{hits}/{len(idx)} flagged points are within 2 steps of an "
              f"injected anomaly")


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
