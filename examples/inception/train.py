"""Inception-v1 ImageNet training example (reference
zoo/.../examples/inception/Train.scala:31-120: Inception_v1_NoAuxClassifier
with SGD + iteration-based warmup/poly decay; python twin
pyzoo/zoo/examples/inception/inception.py).

With --data-dir, trains on ImageNet-style TFRecord or .npz shards (same
loaders as the ResNet example); without, synthetic data measures training
throughput.

Usage:
    python examples/inception/train.py --steps 20 --batch-size 128
"""

import argparse

import numpy as np


def run(image_size=224, batch_size=128, steps=20, classes=1000,
        data_dir=None, epochs=1):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.feature.dataset import FeatureSet
    from analytics_zoo_tpu.models.inception import Inception
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
        SGD,
        warmup_epoch_decay,
    )

    ctx = init_zoo_context("inception v1")
    net = Inception.v1(classes=classes,
                       input_shape=(image_size, image_size, 3))

    if data_dir:
        from analytics_zoo_tpu.feature.imagenet import imagenet_feature_set

        fs = imagenet_feature_set(data_dir, image_size)
    else:
        n = batch_size * steps
        rng = np.random.default_rng(0)
        x = rng.integers(0, 256, size=(n, image_size, image_size, 3),
                         dtype=np.uint8)
        y = rng.integers(0, classes, size=(n,)).astype(np.int32)
        fs = FeatureSet.of(x, y)
        epochs = 1
    fs.transform_on_device(_normalize)

    # Train.scala:83-98: SGD, linear warmup then epoch decay; momentum 0.9,
    # weight decay 1e-4.  steps_per_epoch comes from the ACTUAL dataset so
    # the decay boundaries land at real epochs, not at the synthetic-run
    # step count.
    steps_per_epoch = max(fs.num_samples // batch_size, 1)
    schedule = warmup_epoch_decay(warmup_steps=2 * steps_per_epoch,
                                  steps_per_epoch=steps_per_epoch,
                                  boundaries_epochs=(30, 60),
                                  decay=0.1)
    net.compile(optimizer=SGD(lr=0.065, momentum=0.9, weight_decay=1e-4,
                              schedule=schedule),
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    net.fit(fs, batch_size=batch_size, nb_epoch=epochs)
    return net


def _normalize(batch):
    import jax.numpy as jnp

    x = batch["x"].astype(jnp.float32)
    return {**batch, "x": (x - 127.0) / 59.0}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--epochs", type=int, default=1)
    args = ap.parse_args()
    net = run(args.image_size, args.batch_size, args.steps,
              data_dir=args.data_dir, epochs=args.epochs)
    h = net._estimator.history if net._estimator else []
    if h:
        print(f"final loss {h[-1]['loss']:.4f}, "
              f"{h[-1]['throughput']:.1f} img/s")


if __name__ == "__main__":
    import os
    import sys

    # allow `python examples/<domain>/<script>.py` from anywhere: put the
    # repo root (two levels up) on sys.path before importing the package
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    main()
